"""Hummock-lite state tiering: L0 flush + versioned manifest, recovery,
pinned snapshot reads under concurrent compaction, vacuum safety, and the
Session running end-to-end over the tier (incl. a REAL crash)."""

import os
import subprocess
import sys
import textwrap

import pytest

from risingwave_tpu.common.failpoint import failpoints
from risingwave_tpu.meta.hummock import HummockManager
from risingwave_tpu.storage.hummock import (
    SST_PREFIX, HummockStateStore, HummockVersion, run_compact_task,
)
from risingwave_tpu.storage.object_store import (
    LocalFsObjectStore, MemObjectStore,
)


def _store(**kw):
    kw.setdefault("object_store", MemObjectStore())
    kw.setdefault("inline_compaction", False)
    return HummockStateStore(**kw)


def _fill(st, table=7, epochs=range(1, 6)):
    for e in epochs:
        st.ingest(table, e, {b"k%03d" % e: b"v%d" % e}, set())
        st.commit(e)


class TestHummockStore:
    def test_commit_recover_roundtrip(self, tmp_path):
        d = str(tmp_path / "hm")
        st = HummockStateStore(data_dir=d, inline_compaction=False)
        st.ingest(7, 2, {b"a": b"row-a", b"b": b"row-b"}, set())
        st.commit(2)
        st.ingest(7, 3, {b"c": b"row-c"}, {b"a"})
        st.ingest(9, 3, {b"x": b"row-x"}, set())
        st.commit(3)

        st2 = HummockStateStore(data_dir=d)
        assert st2.committed_epoch == 3
        assert dict(st2.iter_table(7)) == {b"b": b"row-b", b"c": b"row-c"}
        assert dict(st2.iter_table(9)) == {b"x": b"row-x"}

        # compaction folds runs without changing the view
        st2.compact()
        st3 = HummockStateStore(data_dir=d)
        assert dict(st3.iter_table(7)) == {b"b": b"row-b", b"c": b"row-c"}
        assert st3.committed_epoch == 3

    def test_idle_commit_adds_no_runs(self):
        st = _store()
        _fill(st, epochs=range(1, 3))
        n0 = len(st.manager.version.all_runs())
        for e in range(3, 8):
            st.commit(e)                     # nothing staged
        v = st.manager.version
        assert len(v.all_runs()) == n0
        assert v.committed_epoch == 7

    def test_drop_table_then_compact_discards_rows(self):
        st = _store()
        _fill(st, table=5)
        _fill(st, table=6, epochs=range(6, 9))
        st.drop_table(5)
        st.compact()
        # the folded tier holds only the live table
        st2 = HummockStateStore(object_store=st.object_store)
        assert dict(st2.iter_table(5)) == {}
        assert len(dict(st2.iter_table(6))) == 3

    def test_vacuum_no_orphans_after_drop(self):
        """CI vacuum-leak assertion: after drop + compact + vacuum, every
        SST the object store lists is referenced by the current version —
        object-store growth stays bounded."""
        st = _store()
        _fill(st, table=5)
        st.drop_table(5)
        st.compact()                          # also vacuums
        st.vacuum()
        listed = set(st.object_store.list(SST_PREFIX))
        assert listed == set(st.manager.version.all_runs())

    def test_tombstones_survive_until_bottom_compaction(self):
        st = _store()
        st.ingest(7, 1, {b"a": b"1"}, set())
        st.commit(1)
        st.ingest(7, 2, {}, {b"a"})           # delete in a later run
        st.commit(2)
        st2 = HummockStateStore(object_store=st.object_store)
        assert dict(st2.iter_table(7)) == {}
        st.compact()                          # bottom: tombstone dropped
        st3 = HummockStateStore(object_store=st.object_store)
        assert dict(st3.iter_table(7)) == {}


class TestPinnedReads:
    def test_pin_survives_concurrent_rewrite_and_vacuum(self):
        """Acceptance: a reader pinned to a version sees identical
        results while compaction rewrites that version's runs, and vacuum
        afterwards deletes every SST unreferenced by any pinned
        version."""
        st = _store()
        _fill(st, epochs=range(1, 9))
        snap = st.pin()
        before = dict(snap.iter_table(7))
        pinned_runs = set(snap.version.all_runs())
        assert before and pinned_runs

        st.compact()                          # rewrites + vacuums
        # the pinned runs survived vacuum (still referenced by the pin)
        listed = set(st.object_store.list(SST_PREFIX))
        assert pinned_runs <= listed
        # identical results through the pinned snapshot
        assert dict(snap.iter_table(7)) == before
        for e in range(1, 9):
            assert snap.get(7, b"k%03d" % e) == b"v%d" % e

        snap.unpin()
        deleted = st.vacuum()
        assert set(deleted) == pinned_runs - set(
            st.manager.version.all_runs())
        assert set(st.object_store.list(SST_PREFIX)) == set(
            st.manager.version.all_runs())

    def test_vacuum_spares_in_progress_upload(self):
        """Regression: the barrier path PUTs the L0 object before the
        version publish references it; a concurrently running vacuum (the
        compaction pump's) must not eat it in that window."""
        st = _store()
        _fill(st, epochs=range(1, 3))
        name = SST_PREFIX + "e000000000099-test.sst"
        st.manager.begin_upload(name)
        st.object_store.put(name, b"payload")
        assert name not in st.vacuum()          # protected while pending
        assert st.object_store.get(name) is not None
        st.manager.commit_epoch(99, name)       # now referenced
        assert name not in st.vacuum()
        # an aborted upload loses protection and becomes vacuum food
        name2 = SST_PREFIX + "e000000000100-test.sst"
        st.manager.begin_upload(name2)
        st.object_store.put(name2, b"payload")
        st.manager.abort_upload(name2)
        assert name2 in st.vacuum()

    def test_vacuum_spares_inflight_task_outputs(self):
        """Regression: a compactor (possibly another process) writes its
        ``c{task_id}-…`` outputs before the report references them —
        vacuum must skip them mid-task and reap them only if the task is
        cancelled."""
        st = _store()
        _fill(st)
        task = st.manager.get_compact_task(force=True)
        half = f"{SST_PREFIX}c{task.task_id:06d}-000-deadbeef.sst"
        st.object_store.put(half, b"half-written output")
        assert half not in st.vacuum()          # protected mid-task
        st.manager.cancel_compact_task(task.task_id)
        assert half in st.vacuum()              # zombie output reaped

    def test_vacuum_spares_inflight_task_inputs(self):
        st = _store()
        _fill(st)
        task = st.manager.get_compact_task(force=True)
        assert task is not None
        st.vacuum()
        for name in task.inputs:              # still readable mid-task
            assert st.object_store.get(name) is not None
        outputs = run_compact_task(st.object_store, task)
        st.manager.report_compact_task(task.task_id, outputs)
        st.vacuum()
        assert set(st.object_store.list(SST_PREFIX)) == set(outputs)


class TestVersionManager:
    def test_version_swap_is_atomic_and_monotonic(self):
        os_ = MemObjectStore()
        mgr = HummockManager(os_)
        mgr.commit_epoch(1, None)
        v1 = mgr.version
        mgr.log_ddl("CREATE TABLE t (k BIGINT)")
        v2 = mgr.version
        assert v2.vid == v1.vid + 1 and v2.ddl == ("CREATE TABLE t (k BIGINT)",)
        # a fresh manager over the same store sees the same version
        mgr2 = HummockManager(os_)
        assert mgr2.version == v2

    def test_late_report_from_cancelled_task_is_rejected(self):
        st = _store()
        _fill(st)
        task = st.manager.get_compact_task(force=True)
        outputs = run_compact_task(st.object_store, task)
        st.manager.cancel_compact_task(task.task_id)
        assert st.manager.report_compact_task(task.task_id, outputs) \
            is False
        # the zombie's outputs are orphans: vacuum removes them
        st.vacuum()
        for name in outputs:
            assert st.object_store.get(name) is None
        # the version still folds correctly
        st2 = HummockStateStore(object_store=st.object_store)
        assert len(dict(st2.iter_table(7))) == 5

    def test_roundtrip_version_codec(self):
        v = HummockVersion(vid=4, committed_epoch=9, l0=("a", "b"),
                           l1=("c",), ddl=("X",), dropped_tables=(3,))
        assert HummockVersion.from_bytes(v.to_bytes()) == v


class TestHummockFailpoints:
    def test_sst_write_fault_is_atomic(self):
        st = _store()
        _fill(st, epochs=range(1, 3))
        st.ingest(7, 3, {b"k003": b"v3"}, set())
        with failpoints(**{"hummock.sst.write": OSError}):
            with pytest.raises(OSError):
                st.commit(3)
        st2 = HummockStateStore(object_store=st.object_store)
        assert st2.committed_epoch == 2
        assert b"k003" not in dict(st2.iter_table(7))

    def test_torn_sst_object_never_referenced(self):
        st = _store()
        _fill(st, epochs=range(1, 3))
        st.ingest(7, 3, {b"k003": b"v3"}, set())
        with failpoints(**{"hummock.sst.write.partial": OSError}):
            with pytest.raises(OSError):
                st.commit(3)
        # a truncated orphan landed; recovery ignores it, and the SAME
        # process's vacuum eats it — the failed put must have aborted
        # its upload registration (it would otherwise be shielded for
        # the process lifetime)
        assert len(st.vacuum()) == 1
        st2 = HummockStateStore(object_store=st.object_store)
        assert st2.committed_epoch == 2
        assert st2.vacuum() == []

    def test_version_publish_fault_keeps_previous_epoch(self):
        st = _store()
        _fill(st, epochs=range(1, 3))
        st.ingest(7, 3, {b"k003": b"v3"}, set())
        with failpoints(**{"hummock.version.publish": OSError}):
            with pytest.raises(OSError):
                st.commit(3)
        st2 = HummockStateStore(object_store=st.object_store)
        assert st2.committed_epoch == 2     # no lost epochs ≤ committed


class TestSessionOverHummock:
    def test_session_e2e_and_recovery(self, tmp_path):
        from risingwave_tpu.frontend import Session
        d = str(tmp_path / "db")
        s = Session(data_dir=d, state_store="hummock",
                    checkpoint_frequency=1)
        s.run_sql("CREATE TABLE t (k BIGINT, v BIGINT)")
        s.run_sql("""CREATE MATERIALIZED VIEW m AS
                     SELECT k, v * 2 AS d FROM t""")
        for i in range(4):
            s.run_sql(f"INSERT INTO t VALUES ({i}, {i * 10})")
            s.flush()
        assert s.metrics()["storage"]["tier"] == "hummock"
        s.close()

        # plain Session(data_dir=...) auto-detects the hummock tier
        s2 = Session(data_dir=d)
        assert s2.state_store_kind == "hummock"
        assert sorted(s2.mv_rows("m")) == [(i, i * 20) for i in range(4)]
        s2.run_sql("INSERT INTO t VALUES (9, 90)")
        s2.flush()
        assert (9, 180) in s2.mv_rows("m")
        s2.close()

    def test_crash_recovery_loses_only_uncheckpointed(self, tmp_path):
        d = str(tmp_path / "db")
        child = textwrap.dedent(f"""
            import os
            from risingwave_tpu.frontend import Session
            s = Session(data_dir={d!r}, state_store="hummock")
            s.run_sql("CREATE TABLE t (k BIGINT, v BIGINT)")
            s.run_sql("INSERT INTO t VALUES (1,10),(2,20)")
            s.flush()
            s.run_sql("INSERT INTO t VALUES (3,999)")
            s.tick(generate=False, checkpoint=False)  # staged, not durable
            os._exit(0)                               # crash
        """)
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.pop("TPU_LIBRARY_PATH", None)
        res = subprocess.run([sys.executable, "-c", child], env=env,
                             capture_output=True, text=True, timeout=600,
                             cwd=os.path.dirname(os.path.dirname(
                                 os.path.abspath(__file__))))
        assert res.returncode == 0, res.stderr[-2000:]
        from risingwave_tpu.frontend import Session
        s = Session(data_dir=d)
        assert sorted(s.run_sql("SELECT k, v FROM t")) == [(1, 10), (2, 20)]
        s.close()

    def test_session_pin_version_api(self, tmp_path):
        from risingwave_tpu.frontend import Session
        d = str(tmp_path / "db")
        s = Session(data_dir=d, state_store="hummock")
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY)")
        s.run_sql("INSERT INTO t VALUES (1),(2)")
        s.flush()
        with s.pin_version() as snap:
            assert snap.version.committed_epoch == s.store.committed_epoch
            assert s.metrics()["storage"]["pinned_versions"] == 1
        assert s.metrics()["storage"]["pinned_versions"] == 0
        s.close()

    def test_rw_config_reopen_auto_detects_tier(self, tmp_path):
        """Regression: StorageConfig.state_store defaults to None (auto)
        — reopening a hummock dir through rw_config must not silently
        initialize a fresh segment store over it."""
        from risingwave_tpu.common.config import load_config
        from risingwave_tpu.frontend import Session
        d = str(tmp_path / "db")
        s = Session(data_dir=d, state_store="hummock")
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY)")
        s.run_sql("INSERT INTO t VALUES (1)")
        s.flush()
        s.close()
        cfg = load_config(**{"storage.data_dir": d})
        s2 = Session(rw_config=cfg)
        assert s2.state_store_kind == "hummock"
        assert s2.run_sql("SELECT k FROM t") == [(1,)]
        s2.close()

    def test_explicit_tier_mismatch_refuses(self, tmp_path):
        """An explicit state_store that contradicts the dir's actual
        tier must refuse instead of recovering an empty store."""
        from risingwave_tpu.frontend import Session
        d = str(tmp_path / "hmdir")
        s = Session(data_dir=d, state_store="hummock")
        s.run_sql("CREATE TABLE t (k BIGINT)")
        s.flush()
        s.close()
        with pytest.raises(ValueError, match="hummock"):
            Session(data_dir=d, state_store="segment")
        d2 = str(tmp_path / "segdir")
        s3 = Session(data_dir=d2)            # segment by default
        s3.run_sql("CREATE TABLE t (k BIGINT)")
        s3.flush()
        s3.close()
        with pytest.raises(ValueError, match="segment"):
            Session(data_dir=d2, state_store="hummock")

    def test_pin_requires_hummock(self):
        from risingwave_tpu.frontend import Session
        from risingwave_tpu.frontend.session import SqlError
        s = Session()
        with pytest.raises(SqlError, match="hummock"):
            s.pin_version()
        s.close()


class TestHummockBackup:
    def test_backup_restore_hummock_dir(self, tmp_path):
        from risingwave_tpu.frontend import Session
        from risingwave_tpu.storage.backup import (
            create_backup, restore_backup,
        )
        d = str(tmp_path / "db")
        s = Session(data_dir=d, state_store="hummock")
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, v BIGINT)")
        s.run_sql("INSERT INTO t VALUES (1, 10), (2, 20)")
        s.flush()
        s.close()

        bdir = str(tmp_path / "bk")
        desc = create_backup(d, bdir)
        assert desc["tier"] == "hummock"
        assert "hummock/version.json" in desc["files"]

        d2 = str(tmp_path / "restored")
        restore_backup(bdir, d2)
        s2 = Session(data_dir=d2)
        assert s2.state_store_kind == "hummock"
        assert sorted(s2.run_sql("SELECT k, v FROM t")) == [(1, 10), (2, 20)]
        s2.close()
