"""NOT IN (SELECT ...) NULL semantics (ISSUE 2 satellite).

PostgreSQL: ``x NOT IN (sub)`` is TRUE iff x is non-NULL, x matches no
subquery value, AND the subquery produced no NULL (x <> NULL is unknown).
The planner makes the anti join null-aware: NULL probe keys are filtered
below the join; a NULL from the subquery yields zero rows in batch and a
loud, actionable error in streaming (a silent divergence was the bug)."""

import pytest

from risingwave_tpu.frontend import Session


@pytest.fixture()
def sess():
    s = Session()
    s.run_sql("CREATE TABLE a (x BIGINT, tag BIGINT)")
    s.run_sql("CREATE TABLE b (y BIGINT)")
    yield s
    s.close()


class TestBatchNotInNull:
    def test_null_probe_key_never_passes(self, sess):
        sess.run_sql("INSERT INTO a VALUES (1,1),(2,2),(NULL,3),(4,4)")
        sess.run_sql("INSERT INTO b VALUES (1),(3)")
        sess.flush()
        rows = sorted(sess.run_sql(
            "SELECT tag FROM a WHERE x NOT IN (SELECT y FROM b)"))
        assert rows == [(2,), (4,)]          # NULL-keyed row 3 excluded

    def test_null_in_subquery_yields_no_rows(self, sess):
        sess.run_sql("INSERT INTO a VALUES (1,1),(2,2),(4,4)")
        sess.run_sql("INSERT INTO b VALUES (1),(NULL)")
        sess.flush()
        assert sess.run_sql(
            "SELECT tag FROM a WHERE x NOT IN (SELECT y FROM b)") == []

    def test_in_semantics_unchanged(self, sess):
        sess.run_sql("INSERT INTO a VALUES (1,1),(NULL,3),(4,4)")
        sess.run_sql("INSERT INTO b VALUES (1),(NULL)")
        sess.flush()
        rows = sorted(sess.run_sql(
            "SELECT tag FROM a WHERE x IN (SELECT y FROM b)"))
        assert rows == [(1,)]

    def test_known_divergence_null_probe_empty_subquery(self, sess):
        """Documented divergence (frontend/planner.py _plan_in_subquery):
        PG keeps a NULL probe row when the subquery is EMPTY; the static
        probe filter drops it regardless. Pinned here so a behavior
        change is a conscious one."""
        sess.run_sql("INSERT INTO a VALUES (NULL, 1), (5, 2)")
        sess.flush()                           # b stays empty
        rows = sorted(sess.run_sql(
            "SELECT tag FROM a WHERE x NOT IN (SELECT y FROM b)"))
        assert rows == [(2,)]                  # PG would return [(1,),(2,)]

    def test_filtered_subquery_restores_rows(self, sess):
        sess.run_sql("INSERT INTO a VALUES (1,1),(2,2)")
        sess.run_sql("INSERT INTO b VALUES (1),(NULL)")
        sess.flush()
        rows = sess.run_sql(
            "SELECT tag FROM a WHERE x NOT IN "
            "(SELECT y FROM b WHERE y IS NOT NULL)")
        assert rows == [(2,)]


class TestStreamingNotInNull:
    def test_null_probe_key_excluded_from_mv(self, sess):
        sess.run_sql("""CREATE MATERIALIZED VIEW m AS
            SELECT tag FROM a WHERE x NOT IN (SELECT y FROM b)""")
        sess.run_sql("INSERT INTO a VALUES (1,1),(2,2),(NULL,3),(4,4)")
        sess.run_sql("INSERT INTO b VALUES (1),(3)")
        sess.flush()
        assert sorted(sess.mv_rows("m")) == [(2,), (4,)]

    def test_null_in_subquery_fails_loud_not_wrong(self, sess):
        sess.run_sql("""CREATE MATERIALIZED VIEW m AS
            SELECT tag FROM a WHERE x NOT IN (SELECT y FROM b)""")
        sess.run_sql("INSERT INTO a VALUES (1,1),(2,2)")
        sess.run_sql("INSERT INTO b VALUES (NULL)")
        with pytest.raises(Exception):
            sess.flush()
        # the actionable root cause is on the job's failure record
        job = sess.jobs["m"]
        assert job._failure is not None
        assert "NOT IN" in str(job._failure)

    def test_plan_marks_anti_join_null_aware(self, sess):
        from risingwave_tpu.frontend.parser import parse_one
        stmt = parse_one(
            "SELECT tag FROM a WHERE x NOT IN (SELECT y FROM b)")
        plan = sess._plan(stmt.select)
        found = []

        def walk(n):
            if type(n).__name__ == "PJoin":
                found.append(n)
            for c in n.children:
                walk(c)

        walk(plan)
        assert found and found[0].kind == "left_anti"
        assert found[0].null_aware is True
        # ... and the null-aware flag survives the plan-JSON boundary
        # (the contract a remote worker rebuilds the job from)
        from risingwave_tpu.frontend.plan_json import (
            plan_from_json, plan_to_json,
        )
        rt = plan_from_json(plan_to_json(plan), sess.catalog)
        found.clear()
        walk(rt)
        assert found and found[0].null_aware is True
