"""Incremental TopN (VERDICT r2 item 9): the plain-TopN fast path sorts
only a bounded candidate set per barrier; full-sort refills happen only on
candidate exhaustion or threshold breach. Randomized churn cross-checks
the emitted fold against a brute-force host model."""

import asyncio
import random

from risingwave_tpu.common.chunk import (
    OP_DELETE, OP_INSERT, chunk_to_rows, make_chunk,
)
from risingwave_tpu.common.types import INT64, Field, Schema
from risingwave_tpu.ops.topn import OrderSpec
from risingwave_tpu.stream.executor import collect_until_barrier
from risingwave_tpu.stream.message import Barrier
from risingwave_tpu.stream.source import MockSource
from risingwave_tpu.stream.top_n import TopNExecutor

S = Schema((Field("k", INT64), Field("v", INT64)))


def _run(messages, **kw):
    n_b = sum(1 for m in messages if isinstance(m, Barrier))
    ex = TopNExecutor(MockSource(S, messages), order=[OrderSpec(1)],
                      pk_indices=[0], table_capacity=1 << 12, **kw)

    async def go():
        chunks, _, _ = await collect_until_barrier(ex.execute(), n_b)
        return chunks
    return ex, asyncio.run(go())


def _fold(chunks):
    acc = {}
    for c in chunks:
        for op, row in chunk_to_rows(c, S, with_ops=True):
            acc[row] = acc.get(row, 0) + (1 if op in (0, 3) else -1)
    return {row for row, n in acc.items() if n > 0}


def _host_topn(rows, offset, limit):
    ordered = sorted(rows.items(), key=lambda kv: (kv[1], kv[0]))
    return {(k, v) for k, v in ordered[offset:offset + limit]}


class TestIncremental:
    def test_fast_path_used_and_correct(self):
        msgs = [Barrier.new(1)]
        for e in range(2, 8):
            rows = [(e * 100 + i, random.randint(0, 1000)) for i in range(20)]
            msgs.append(make_chunk(S, rows, capacity=32))
            msgs.append(Barrier.new(e))
        ex, chunks = _run(msgs, offset=0, limit=5)
        assert ex.use_incremental
        assert ex.n_fast_flushes >= 4   # most barriers avoid the full sort

    def test_randomized_churn_matches_host_model(self):
        rng = random.Random(7)
        live = {}
        msgs = [Barrier.new(1)]
        epoch = 2
        for _ in range(30):
            rows, ops = [], []
            for _ in range(rng.randint(1, 12)):
                if live and rng.random() < 0.45:
                    k = rng.choice(list(live))
                    rows.append((k, live.pop(k)))
                    ops.append(OP_DELETE)
                else:
                    k = rng.randint(0, 10_000)
                    v = rng.randint(0, 500)   # heavy ties
                    if k in live:
                        continue
                    live[k] = v
                    rows.append((k, v))
                    ops.append(OP_INSERT)
            if rows:
                msgs.append(make_chunk(S, rows, ops=ops, capacity=16))
            msgs.append(Barrier.new(epoch))
            epoch += 1
        ex, chunks = _run(msgs, offset=0, limit=7)
        assert _fold(chunks) == _host_topn(live, 0, 7)
        assert ex.n_fast_flushes > 0    # fast path actually exercised

    def test_delete_drain_forces_refill(self):
        """Delete the whole window repeatedly: underflow must trigger
        refills and promotion from beyond the candidate set."""
        rows = [(i, i) for i in range(600)]
        msgs = [Barrier.new(1),
                make_chunk(S, rows[:512], capacity=512),
                Barrier.new(2),
                make_chunk(S, rows[512:], capacity=512),
                Barrier.new(3)]
        # delete the current top-300 (covers cand_keep=256 twice over)
        epoch = 4
        for lo in range(0, 300, 50):
            dels = [(i, i) for i in range(lo, lo + 50)]
            msgs.append(make_chunk(S, dels, ops=[OP_DELETE] * 50,
                                   capacity=64))
            msgs.append(Barrier.new(epoch))
            epoch += 1
        ex, chunks = _run(msgs, offset=0, limit=3)
        expect = {(i, i) for i in range(300, 303)}
        assert _fold(chunks) == expect
        assert ex.n_refills >= 1

    def test_offset_window(self):
        rows = [(i, i * 10) for i in range(50)]
        msgs = [Barrier.new(1), make_chunk(S, rows, capacity=64),
                Barrier.new(2)]
        ex, chunks = _run(msgs, offset=5, limit=3)
        assert _fold(chunks) == {(5, 50), (6, 60), (7, 70)}

    def test_idle_barrier_skips_flush(self):
        rows = [(i, i) for i in range(100)]
        msgs = [Barrier.new(1), make_chunk(S, rows, capacity=128),
                Barrier.new(2), Barrier.new(3), Barrier.new(4)]
        ex, chunks = _run(msgs, offset=0, limit=5)
        # idle barriers (3, 4) do no flush work at all
        assert ex.n_fast_flushes + ex.n_refills == 1
        assert _fold(chunks) == {(i, i) for i in range(5)}
