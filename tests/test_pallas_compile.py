"""Pallas compile CI proxy: lower both TPU kernels to StableHLO with the
embedded Mosaic payload WITHOUT executing anything (VERDICT next-round
item 1's chip-less fallback).

``jax.jit(...).trace(...).lower(lowering_platforms=("tpu",))`` runs the
full Pallas→Mosaic lowering pipeline on any host — kernel tracing errors,
unsupported ops, and block-spec/shape mismatches all surface HERE, years
before a chip sees the program (only the final Mosaic→LLO device compile
is out of reach). scripts/check.sh runs this file, so kernel compile
breakage fails CI even while the tunnel is down."""

import jax
import jax.numpy as jnp
import pytest

from risingwave_tpu.ops.interval_join import interval_match_pallas_call
from risingwave_tpu.ops.pallas_rank import rank_totals_pallas_call


def _lower_tpu(fn, *args) -> str:
    return jax.jit(fn).trace(*args).lower(
        lowering_platforms=("tpu",)).as_text()


def test_rank_kernel_lowers_for_tpu():
    # the bench shapes (N=4096, W=128) — exactly what the chip will run
    ident = jnp.zeros(4096, jnp.int32)
    matches = jnp.zeros((4096, 128), jnp.bool_)
    text = _lower_tpu(lambda a, m: rank_totals_pallas_call(a, m),
                      ident, matches)
    assert "tpu_custom_call" in text      # the Mosaic kernel is embedded
    assert "stablehlo" in text


def test_interval_match_kernel_lowers_for_tpu():
    nb, w = 1 << 15, 128                   # Q7_BUCKETS x Q7_LANES
    vals = jnp.zeros((nb, w), jnp.int64)
    occ = jnp.zeros((nb, w), jnp.bool_)
    mx = jnp.zeros(nb, jnp.int64)
    live = jnp.zeros(nb, jnp.bool_)
    text = _lower_tpu(
        lambda v, o, om, ol, nm, nl:
        interval_match_pallas_call(v, o, om, ol, nm, nl),
        vals, occ, mx, live, mx, live)
    assert "tpu_custom_call" in text
    assert "stablehlo" in text


def test_lowering_is_compile_only():
    """The proxy must never execute: lowering a kernel whose EXECUTION
    would fail on CPU still succeeds (no backend dispatch happens)."""
    ident = jnp.zeros(256, jnp.int32)
    matches = jnp.zeros((256, 128), jnp.bool_)
    # no TPU in CI — executing rank_totals_pallas_call(interpret=False)
    # here would die; lowering for TPU is pure compilation
    text = _lower_tpu(lambda a, m: rank_totals_pallas_call(a, m),
                      ident, matches)
    assert len(text) > 0


# ---------------------------------------------------------------------------
# New fused surfaces (q8 session windows, TPC-H q3, multi-job co-scheduled
# epochs): lowered for platform "tpu" WITHOUT executing, so a fused core
# that stopped compiling for the chip fails CI while the tunnel is down —
# same contract as the Pallas kernels above.
# ---------------------------------------------------------------------------


def _lower_tpu_jitted(jitted, *args) -> str:
    return jitted.trace(*args).lower(lowering_platforms=("tpu",)).as_text()


def test_fused_session_epoch_lowers_for_tpu():
    from risingwave_tpu.common import INT64, TIMESTAMP
    from risingwave_tpu.common.types import Field, Schema
    from risingwave_tpu.connector import NexmarkConfig
    from risingwave_tpu.connector.nexmark import DeviceBidGenerator
    from risingwave_tpu.expr import col
    from risingwave_tpu.ops.fused_epoch import fused_source_session_epoch
    from risingwave_tpu.ops.session_window import SessionWindowCore

    core = SessionWindowCore(
        Schema((Field("bidder", INT64), Field("ts", TIMESTAMP))),
        key_col=0, ts_col=1, gap_us=500_000,
        capacity=1 << 12, closed_capacity=1 << 12)
    gen = DeviceBidGenerator(NexmarkConfig(chunk_capacity=512))
    fused = fused_source_session_epoch(
        gen.chunk_fn(), [col(1, INT64), col(5, TIMESTAMP)], core, 512,
        donate=False)
    text = _lower_tpu_jitted(fused, core.init_state(), jnp.int64(0),
                             jax.random.PRNGKey(0), 4, jnp.int64(0))
    assert "stablehlo" in text and ("while" in text or "scan" in text)


def test_fused_q3_epoch_lowers_for_tpu():
    from risingwave_tpu.connector.tpch import (
        DeviceQ3Generator, Q3_CUTOFF_DAYS, TpchQ3Config,
    )
    from risingwave_tpu.ops.fused_epoch import fused_source_q3_epoch
    from risingwave_tpu.ops.stream_q3 import Q3Core

    core = Q3Core(Q3_CUTOFF_DAYS, orders_capacity=1 << 12,
                  agg_capacity=1 << 12)
    gen = DeviceQ3Generator(TpchQ3Config(chunk_capacity=512))
    fused = fused_source_q3_epoch(gen.chunk_fn(), core, 512, donate=False)
    text = _lower_tpu_jitted(fused, core.init_state(), jnp.int64(0),
                             jax.random.PRNGKey(0), 4)
    assert "stablehlo" in text and ("while" in text or "scan" in text)


def test_multi_job_epoch_lowers_for_tpu():
    """The co-scheduled group epoch (vmapped over the job axis) lowers
    for the chip — the tentpole surface compiles even while the tunnel
    is down."""
    from risingwave_tpu.common import INT64, TIMESTAMP
    from risingwave_tpu.connector import BID_SCHEMA, NexmarkConfig
    from risingwave_tpu.connector.nexmark import DeviceBidGenerator
    from risingwave_tpu.expr import Literal, call, col
    from risingwave_tpu.expr.agg import count_star
    from risingwave_tpu.ops import fused_multi as fm
    from risingwave_tpu.stream import HashAggExecutor, ProjectExecutor
    from risingwave_tpu.stream.source import MockSource

    exprs = [call("tumble_start", col(5, TIMESTAMP),
                  Literal(1_000_000, INT64)), col(0, INT64)]
    proj = ProjectExecutor(MockSource(BID_SCHEMA, []), exprs,
                           names=("ws", "a"))
    agg = HashAggExecutor(proj, [0, 1], [count_star()],
                          table_capacity=1 << 12, out_capacity=512)
    gen = DeviceBidGenerator(NexmarkConfig(chunk_capacity=512))
    multi = fm.fused_multi_agg_epoch(gen.chunk_fn(), exprs, agg.core,
                                     512, donate=False)
    stacked = fm.stack_states([agg.core.init_state() for _ in range(8)])
    starts = jnp.zeros(8, jnp.int64)
    keys = jnp.stack([jax.random.PRNGKey(j) for j in range(8)])
    text = _lower_tpu_jitted(multi, stacked, starts, keys, 4)
    assert "stablehlo" in text and ("while" in text or "scan" in text)


@pytest.mark.parametrize("shape", ["agg", "join"])
def test_sharded_fused_epoch_lowers_for_tpu(shape):
    """The mesh-sharded fused epochs (ops/fused_sharded.py) — shard_map
    around the solo epoch body with the in-dispatch all_to_all shuffle —
    lower for platform "tpu" chip-free over the virtual CPU mesh, so a
    sharded surface that stopped compiling for the chip fails CI while
    the tunnel is down."""
    from risingwave_tpu.common import INT64, TIMESTAMP
    from risingwave_tpu.common.types import Field, Schema
    from risingwave_tpu.connector import NexmarkConfig
    from risingwave_tpu.connector.nexmark import DeviceBidGenerator
    from risingwave_tpu.expr import Literal, call, col
    from risingwave_tpu.expr.agg import count_star
    from risingwave_tpu.ops.fused_sharded import SHARDED_EPOCH_BUILDERS
    from risingwave_tpu.ops.grouped_agg import AggCore
    from risingwave_tpu.ops.interval_join import IntervalJoinCore
    from risingwave_tpu.ops.fused_multi import stack_states
    from risingwave_tpu.parallel.sharded_agg import make_mesh

    n = 4
    assert len(jax.devices()) >= n
    mesh = make_mesh(n)
    gen = DeviceBidGenerator(NexmarkConfig(chunk_capacity=256))
    exprs = [call("tumble_start", col(5, TIMESTAMP),
                  Literal(5_000, INT64)), col(0, INT64), col(2, INT64)]
    if shape == "agg":
        core = AggCore([INT64, INT64], [0, 1], [count_star()],
                       1 << 10, 128)
        builder = SHARDED_EPOCH_BUILDERS["source_agg"]
    else:
        core = IntervalJoinCore(
            Schema((Field("ws", TIMESTAMP), Field("auction", INT64),
                    Field("price", INT64))),
            ts_col=0, val_col=2, window_us=5_000, n_buckets=256,
            lane_width=64)
        builder = SHARDED_EPOCH_BUILDERS["source_join"]
    fused = builder(gen.chunk_fn(), exprs, core, 256, mesh)
    stacked = stack_states([core.init_state() for _ in range(n)])
    text = _lower_tpu_jitted(fused, stacked, jnp.int64(0),
                             jax.random.PRNGKey(0), 4)
    assert "stablehlo" in text and ("while" in text or "scan" in text)
    assert "all-to-all" in text or "all_to_all" in text


@pytest.mark.parametrize("shape", ["session", "q3"])
def test_sharded_q8_q3_epochs_lower_for_tpu(shape):
    """The two NEW shard_map epochs (PR 13: sharded q8 session windows
    and sharded TPC-H q3 with its in-dispatch global top-n) lower for
    platform "tpu" chip-free, with the in-dispatch all_to_all visible
    in the StableHLO — same CI contract as the q5/q7 sharded surfaces."""
    from risingwave_tpu.common import INT64, TIMESTAMP
    from risingwave_tpu.common.types import Field, Schema
    from risingwave_tpu.connector import NexmarkConfig
    from risingwave_tpu.connector.nexmark import DeviceBidGenerator
    from risingwave_tpu.connector.tpch import (
        DeviceQ3Generator, Q3_CUTOFF_DAYS, TpchQ3Config,
    )
    from risingwave_tpu.expr import col
    from risingwave_tpu.ops.fused_multi import stack_states
    from risingwave_tpu.ops.fused_sharded import SHARDED_EPOCH_BUILDERS
    from risingwave_tpu.ops.session_window import SessionWindowCore
    from risingwave_tpu.ops.stream_q3 import Q3Core
    from risingwave_tpu.parallel.sharded_agg import make_mesh

    n = 4
    mesh = make_mesh(n)
    if shape == "session":
        core = SessionWindowCore(
            Schema((Field("bidder", INT64), Field("ts", TIMESTAMP))),
            key_col=0, ts_col=1, gap_us=5_000,
            capacity=1 << 10, closed_capacity=1 << 10)
        gen = DeviceBidGenerator(NexmarkConfig(chunk_capacity=256))
        fused = SHARDED_EPOCH_BUILDERS["source_session"](
            gen.chunk_fn(), [col(1, INT64), col(5, TIMESTAMP)], core,
            256, mesh)
        args = (jnp.int64(0), jax.random.PRNGKey(0), 4, jnp.int64(0))
    else:
        core = Q3Core(Q3_CUTOFF_DAYS, orders_capacity=1 << 10,
                      agg_capacity=1 << 10)
        gen = DeviceQ3Generator(TpchQ3Config(chunk_capacity=256))
        fused = SHARDED_EPOCH_BUILDERS["source_q3"](
            gen.chunk_fn(), core, 256, mesh)
        args = (jnp.int64(0), jax.random.PRNGKey(0), 4)
    stacked = stack_states([core.init_state() for _ in range(n)])
    text = _lower_tpu_jitted(fused, stacked, *args)
    assert "stablehlo" in text and ("while" in text or "scan" in text)
    assert "all-to-all" in text or "all_to_all" in text
    if shape == "q3":
        # the global top-n flush all_gathers the candidate union
        assert "all-gather" in text or "all_gather" in text


def test_sharded_group_epoch_lowers_for_tpu():
    """The K×S co-scheduled group epoch (fusion surface 6:
    vmap-over-jobs inside shard_map with the hand-batched group
    all_to_all) lowers for the chip — the tentpole surface compiles
    even while the tunnel is down."""
    from risingwave_tpu.common import INT64, TIMESTAMP
    from risingwave_tpu.connector import NexmarkConfig
    from risingwave_tpu.connector.nexmark import DeviceBidGenerator
    from risingwave_tpu.expr import Literal, call, col
    from risingwave_tpu.expr.agg import count_star
    from risingwave_tpu.ops.fused_multi import stack_states
    from risingwave_tpu.ops.fused_sharded import SHARDED_EPOCH_BUILDERS
    from risingwave_tpu.ops.grouped_agg import AggCore
    from risingwave_tpu.parallel.sharded_agg import make_mesh

    n, jobs = 4, 8
    mesh = make_mesh(n)
    exprs = [call("tumble_start", col(5, TIMESTAMP),
                  Literal(1_000_000, INT64)), col(0, INT64)]
    core = AggCore([INT64, INT64], [0, 1], [count_star()], 1 << 10, 128)
    gen = DeviceBidGenerator(NexmarkConfig(chunk_capacity=256))
    fused = SHARDED_EPOCH_BUILDERS["group_agg"](
        gen.chunk_fn(), exprs, core, 256, mesh)
    per_job = [stack_states([core.init_state() for _ in range(n)])
               for _ in range(jobs)]
    stacked = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs, axis=1), *per_job)
    starts = jnp.zeros(jobs, jnp.int64)
    keys = jnp.stack([jax.random.PRNGKey(j) for j in range(jobs)])
    nos = jnp.zeros(jobs, jnp.int64)
    text = _lower_tpu_jitted(fused, stacked, starts, keys, nos, 4)
    assert "stablehlo" in text and ("while" in text or "scan" in text)
    assert "all-to-all" in text or "all_to_all" in text


def test_sharded_equi_join_epoch_lowers_for_tpu():
    """The generic sharded-fused equi-join epoch (JoinCore under
    shard_map, k chunks per dispatch) lowers for platform "tpu"
    chip-free with the all_to_all routing visible."""
    from risingwave_tpu.common import INT64
    from risingwave_tpu.common.types import Field, Schema
    from risingwave_tpu.ops.fused_multi import stack_states
    from risingwave_tpu.ops.fused_sharded import SHARDED_EPOCH_BUILDERS
    from risingwave_tpu.ops.join_state import JoinCore, JoinType
    from risingwave_tpu.parallel.sharded_agg import make_mesh
    from risingwave_tpu.common.chunk import Column, StreamChunk

    n, k, cap = 4, 3, 64
    mesh = make_mesh(n)
    ls = Schema((Field("k", INT64), Field("v", INT64)))
    rs = Schema((Field("k", INT64), Field("w", INT64)))
    core = JoinCore(ls, rs, [0], [0], JoinType.INNER,
                    key_capacity=1 << 8, bucket_width=8)
    fused = SHARDED_EPOCH_BUILDERS["equi_join"](core, mesh, [0], [0])
    stacked = stack_states([core.init_state() for _ in range(n)])
    cols = tuple(Column(jnp.zeros((n, k, cap), jnp.int64),
                        jnp.zeros((n, k, cap), jnp.bool_))
                 for _ in range(2))
    batch = StreamChunk(jnp.zeros((n, k, cap), jnp.int8),
                        jnp.zeros((n, k, cap), jnp.bool_), cols)
    text = fused.trace(stacked, batch, side="left").lower(
        lowering_platforms=("tpu",)).as_text()
    assert "stablehlo" in text and ("while" in text or "scan" in text)
    assert "all-to-all" in text or "all_to_all" in text


@pytest.mark.parametrize("tier", ["padded", "mega"])
def test_hetero_tick_compiler_epochs_lower_for_tpu(tier):
    """Both tick-compiler dispatch tiers (ISSUE 19: the skeletonized
    padded supergroup epoch and the concatenated mega-epoch) lower for
    platform "tpu" chip-free — same CI contract as every other fused
    surface."""
    from risingwave_tpu.common import INT64, TIMESTAMP
    from risingwave_tpu.connector import BID_SCHEMA, NexmarkConfig
    from risingwave_tpu.connector.nexmark import DeviceBidGenerator
    from risingwave_tpu.expr import Literal, call, col
    from risingwave_tpu.expr.agg import agg as agg_call, count_star
    from risingwave_tpu.ops.fused_hetero import (
        build_mega_epoch, build_padded_group_epoch,
    )
    from risingwave_tpu.ops.fused_multi import stack_states
    from risingwave_tpu.ops.grouped_agg import AggCore
    from risingwave_tpu.stream.coschedule import FusedJobSpec
    from risingwave_tpu.stream.tick_compiler import skeletonize_exprs

    import numpy as np

    cap = 256
    gen = DeviceBidGenerator(NexmarkConfig(chunk_capacity=cap))
    exprs = (call("tumble_start", col(5, TIMESTAMP),
                  Literal(1_000_000, INT64)), col(0, INT64),
             col(2, INT64))
    core = AggCore([INT64, INT64], [0, 1], [count_star()], 1 << 10, cap)
    if tier == "padded":
        jobs = 8
        skel, hole_types, params = skeletonize_exprs(
            exprs, len(BID_SCHEMA))
        fused = build_padded_group_epoch(gen.chunk_fn(), skel, core,
                                         cap, donate=False)
        stacked = stack_states([core.init_state()
                                for _ in range(jobs)])
        param_cols = tuple(
            jnp.asarray(np.full(jobs, params[h], t.np_dtype))
            for h, t in enumerate(hole_types))
        args = (stacked, jnp.zeros(jobs, jnp.int64),
                jnp.stack([jax.random.PRNGKey(j) for j in range(jobs)]),
                jnp.zeros(jobs, jnp.int64), param_cols, 4)
    else:
        other = AggCore([INT64], [1], [count_star(),
                                       agg_call("max", 2, INT64)],
                        1 << 10, cap)
        specs = [
            FusedJobSpec("agg", ("agg", ("nexmark_bid", cap)),
                         gen.chunk_fn(), exprs, core, cap, seed=0),
            FusedJobSpec("agg", ("agg", ("nexmark_bid", cap)),
                         gen.chunk_fn(), exprs, other, cap, seed=1),
        ]
        fused = build_mega_epoch(specs, donate=False)
        args = ((core.init_state(), other.init_state()),
                jnp.zeros(2, jnp.int64),
                jnp.stack([jax.random.PRNGKey(j) for j in range(2)]),
                jnp.zeros(2, jnp.int64), 4)
    text = _lower_tpu_jitted(fused, *args)
    assert "stablehlo" in text and ("while" in text or "scan" in text)
