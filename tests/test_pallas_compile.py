"""Pallas compile CI proxy: lower both TPU kernels to StableHLO with the
embedded Mosaic payload WITHOUT executing anything (VERDICT next-round
item 1's chip-less fallback).

``jax.jit(...).trace(...).lower(lowering_platforms=("tpu",))`` runs the
full Pallas→Mosaic lowering pipeline on any host — kernel tracing errors,
unsupported ops, and block-spec/shape mismatches all surface HERE, years
before a chip sees the program (only the final Mosaic→LLO device compile
is out of reach). scripts/check.sh runs this file, so kernel compile
breakage fails CI even while the tunnel is down."""

import jax
import jax.numpy as jnp
import pytest

from risingwave_tpu.ops.interval_join import interval_match_pallas_call
from risingwave_tpu.ops.pallas_rank import rank_totals_pallas_call


def _lower_tpu(fn, *args) -> str:
    return jax.jit(fn).trace(*args).lower(
        lowering_platforms=("tpu",)).as_text()


def test_rank_kernel_lowers_for_tpu():
    # the bench shapes (N=4096, W=128) — exactly what the chip will run
    ident = jnp.zeros(4096, jnp.int32)
    matches = jnp.zeros((4096, 128), jnp.bool_)
    text = _lower_tpu(lambda a, m: rank_totals_pallas_call(a, m),
                      ident, matches)
    assert "tpu_custom_call" in text      # the Mosaic kernel is embedded
    assert "stablehlo" in text


def test_interval_match_kernel_lowers_for_tpu():
    nb, w = 1 << 15, 128                   # Q7_BUCKETS x Q7_LANES
    vals = jnp.zeros((nb, w), jnp.int64)
    occ = jnp.zeros((nb, w), jnp.bool_)
    mx = jnp.zeros(nb, jnp.int64)
    live = jnp.zeros(nb, jnp.bool_)
    text = _lower_tpu(
        lambda v, o, om, ol, nm, nl:
        interval_match_pallas_call(v, o, om, ol, nm, nl),
        vals, occ, mx, live, mx, live)
    assert "tpu_custom_call" in text
    assert "stablehlo" in text


def test_lowering_is_compile_only():
    """The proxy must never execute: lowering a kernel whose EXECUTION
    would fail on CPU still succeeds (no backend dispatch happens)."""
    ident = jnp.zeros(256, jnp.int32)
    matches = jnp.zeros((256, 128), jnp.bool_)
    # no TPU in CI — executing rank_totals_pallas_call(interpret=False)
    # here would die; lowering for TPU is pure compilation
    text = _lower_tpu(lambda a, m: rank_totals_pallas_call(a, m),
                      ident, matches)
    assert len(text) > 0
