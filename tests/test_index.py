"""CREATE INDEX: incremental index arrangements + batch point lookups
(VERDICT r4 missing #5; reference: src/frontend/src/handler/create_index.rs
— an index is a re-keyed StreamMaterialize; index selection
src/frontend/src/optimizer/rule/index_selection_rule.rs)."""

import os
import tempfile

import pytest

from risingwave_tpu.batch.executors import (
    BatchFilter, BatchProject, RowSeqScan,
)
from risingwave_tpu.batch.lower import lower_plan
from risingwave_tpu.frontend import Session
from risingwave_tpu.frontend.parser import parse_sql


def _plan(s, sql):
    return s._plan(parse_sql(sql)[0].select)


def test_index_create_maintain_lookup():
    s = Session()
    s.run_sql("CREATE TABLE t (id BIGINT PRIMARY KEY, k BIGINT, v VARCHAR)")
    s.run_sql("CREATE INDEX ix_k ON t (k)")
    assert "ix_k" in s.catalog.indexes
    s.run_sql("INSERT INTO t VALUES (1, 10, 'a'), (2, 20, 'b'), "
              "(3, 10, 'c')")
    s.tick()
    # the lookup goes through the index arrangement: prefix-scan executor
    plan = _plan(s, "SELECT id, v FROM t WHERE k = 10")
    lowered = lower_plan(plan, s.store, catalog=s.catalog)
    node = lowered
    while not isinstance(node, RowSeqScan):
        node = node.input
    assert node.prefix is not None, "expected an index prefix scan"
    # and the answers are right, through the public API
    assert sorted(s.run_sql("SELECT id, v FROM t WHERE k = 10")) == [
        (1, "a"), (3, "c")]
    # index maintenance is incremental: updates and deletes flow
    s.run_sql("UPDATE t SET k = 10 WHERE id = 2")
    s.tick()
    assert sorted(s.run_sql("SELECT id FROM t WHERE k = 10")) == [
        (1,), (2,), (3,)]
    s.run_sql("DELETE FROM t WHERE id = 1")
    s.tick()
    assert sorted(s.run_sql("SELECT id FROM t WHERE k = 10")) == [
        (2,), (3,)]
    s.close()


def test_composite_index_prefix_match():
    s = Session()
    s.run_sql("CREATE TABLE t (id BIGINT PRIMARY KEY, a BIGINT, b BIGINT, "
              "v BIGINT)")
    s.run_sql("CREATE INDEX ix_ab ON t (a, b)")
    s.run_sql("INSERT INTO t VALUES (1, 1, 1, 10), (2, 1, 2, 20), "
              "(3, 2, 1, 30)")
    s.tick()
    # full composite equality
    assert s.run_sql("SELECT v FROM t WHERE a = 1 AND b = 2") == [(20,)]
    # leading-column-only equality still uses the prefix
    plan = _plan(s, "SELECT v FROM t WHERE a = 1")
    lowered = lower_plan(plan, s.store, catalog=s.catalog)
    node = lowered
    while not isinstance(node, RowSeqScan):
        node = node.input
    assert node.prefix is not None and len(node.prefix) == 1
    assert sorted(s.run_sql("SELECT v FROM t WHERE a = 1")) == [
        (10,), (20,)]
    # equality on a NON-leading column alone cannot use the index
    plan = _plan(s, "SELECT v FROM t WHERE b = 1")
    lowered = lower_plan(plan, s.store, catalog=s.catalog)
    node = lowered
    while not isinstance(node, RowSeqScan):
        node = node.input
    assert node.prefix is None
    s.close()


def test_index_survives_recovery_and_drop():
    with tempfile.TemporaryDirectory() as d:
        data = os.path.join(d, "data")
        s = Session(data_dir=data)
        s.run_sql("CREATE TABLE t (id BIGINT PRIMARY KEY, k BIGINT)")
        s.run_sql("CREATE INDEX ix ON t (k)")
        s.run_sql("INSERT INTO t VALUES (1, 5), (2, 7)")
        s.tick()
        s.run_sql("FLUSH")
        s.close()

        s2 = Session(data_dir=data)
        assert "ix" in s2.catalog.indexes
        assert s2.run_sql("SELECT id FROM t WHERE k = 7") == [(2,)]
        # still maintained after recovery
        s2.run_sql("INSERT INTO t VALUES (3, 7)")
        s2.tick()
        assert sorted(s2.run_sql("SELECT id FROM t WHERE k = 7")) == [
            (2,), (3,)]
        s2.run_sql("DROP INDEX ix")
        assert "ix" not in s2.catalog.indexes
        assert not any(n.startswith("__idx_ix")
                       for n in s2.catalog.mvs)
        # queries fall back to full scans, still correct
        assert sorted(s2.run_sql("SELECT id FROM t WHERE k = 7")) == [
            (2,), (3,)]
        s2.close()


def test_index_on_mv():
    s = Session()
    s.run_sql("CREATE TABLE t (id BIGINT PRIMARY KEY, k BIGINT, v BIGINT)")
    s.run_sql("CREATE MATERIALIZED VIEW agg AS "
              "SELECT k, sum(v) AS sv FROM t GROUP BY k")
    s.run_sql("CREATE INDEX ix_sv ON agg (sv)")
    s.run_sql("INSERT INTO t VALUES (1, 1, 10), (2, 2, 20)")
    s.tick()
    assert sorted(s.run_sql("SELECT k FROM agg WHERE sv = 10")) == [(1,)]
    s.run_sql("INSERT INTO t VALUES (4, 1, 5)")     # k=1 moves to 15
    s.tick()
    assert s.run_sql("SELECT k FROM agg WHERE sv = 15") == [(1,)]
    assert s.run_sql("SELECT k FROM agg WHERE sv = 10") == []
    assert s.run_sql("SELECT k FROM agg WHERE sv = 20") == [(2,)]
    s.close()


def test_drop_base_cascades_to_index():
    """DROP TABLE removes dependent indexes — a dangling arrangement must
    not serve the dropped table's rows to a re-created namesake."""
    s = Session()
    s.run_sql("CREATE TABLE t (id BIGINT PRIMARY KEY, k BIGINT)")
    s.run_sql("CREATE INDEX ix ON t (k)")
    s.run_sql("INSERT INTO t VALUES (2, 7)")
    s.tick()
    assert s.run_sql("SELECT id FROM t WHERE k = 7") == [(2,)]
    s.run_sql("DROP TABLE t")
    assert "ix" not in s.catalog.indexes
    assert not any(n.startswith("__idx_") for n in s.catalog.mvs)
    s.run_sql("CREATE TABLE t (id BIGINT PRIMARY KEY, k BIGINT)")
    assert s.run_sql("SELECT id FROM t WHERE k = 7") == []
    s.run_sql("INSERT INTO t VALUES (9, 7)")
    s.tick()
    assert s.run_sql("SELECT id FROM t WHERE k = 7") == [(9,)]
    s.close()


def test_index_recovery_with_workers(tmp_path):
    """A data dir whose DDL log contains CREATE INDEX must reopen fine
    with worker placement enabled (the index replays session-local)."""
    data = str(tmp_path / "data")
    s = Session(data_dir=data)
    s.run_sql("CREATE TABLE t (id BIGINT PRIMARY KEY, k BIGINT)")
    s.run_sql("CREATE INDEX ix ON t (k)")
    s.run_sql("INSERT INTO t VALUES (1, 5)")
    s.tick()
    s.run_sql("FLUSH")
    s.close()
    s2 = Session(data_dir=data, workers=1)
    try:
        assert "ix" in s2.catalog.indexes
        assert s2.run_sql("SELECT id FROM t WHERE k = 5") == [(1,)]
    finally:
        s2.close()


def test_index_errors():
    s = Session()
    s.run_sql("CREATE TABLE t (id BIGINT PRIMARY KEY, k BIGINT)")
    with pytest.raises(Exception):
        s.run_sql("CREATE INDEX ix ON t (nope)")
    s.run_sql("CREATE SOURCE src (a BIGINT) WITH (connector = 'datagen')")
    with pytest.raises(Exception):
        s.run_sql("CREATE INDEX ix2 ON src (a)")
    s.close()
