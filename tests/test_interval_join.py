"""Bucketed interval-join core (ops/interval_join.py): output parity with
the executor q7 pipeline (HashAgg max → HashJoin price==max), retraction
included, plus checkpoint/recovery, ring turnover, band filter, and
Pallas/jnp kernel parity.

Parity schedule note: a streaming join's intermediate churn depends on the
intra-epoch interleaving of probe chunks vs the agg's flush chunks (any
interleaving is a valid Chandy-Lamport cut; only the net effect is
schedule-independent). The fused core implements the canonical schedule —
all probe chunks of an epoch, then the build flush — which is exactly what
the epoch-batched bench source delivers; the executor run below pins the
same schedule by gating the build-side source on probe progress."""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np

from risingwave_tpu.common import INT64, Schema, chunk_to_rows, make_chunk
from risingwave_tpu.common.chunk import OP_DELETE, OP_INSERT
from risingwave_tpu.expr import call, col
from risingwave_tpu.expr.agg import agg as agg_call
from risingwave_tpu.ops.interval_join import (
    IntervalJoinCore, interval_match_jnp, interval_match_pallas,
)
from risingwave_tpu.stream import (
    Barrier, HashAggExecutor, HashJoinExecutor,
)
from risingwave_tpu.stream.executor import Executor

CAP = 64
WINDOW = 100

# probe rows: (window_start, auction, price)
PROBE_SCHEMA = Schema.of(("ws", INT64), ("auction", INT64), ("price", INT64))
PRE_SCHEMA = Schema.of(("ws", INT64), ("price", INT64))


def pchunk(rows):
    return make_chunk(PROBE_SCHEMA, rows, capacity=CAP)


# ---------------------------------------------------------------------------
# executor pipeline, pinned to the canonical probe-then-flush schedule
# ---------------------------------------------------------------------------


class _ProbeSource(Executor):
    """MockSource that releases one gate per epoch AFTER its chunks were
    consumed (just before yielding the epoch's barrier)."""

    identity = "ProbeSource"

    def __init__(self, schema: Schema, messages, gates):
        self.schema = schema
        self._messages = list(messages)
        self._gates = gates
        self._epoch_i = 0

    async def execute(self):
        for m in self._messages:
            if isinstance(m, Barrier):
                self._gates[self._epoch_i].set()
                self._epoch_i += 1
            yield m
            await asyncio.sleep(0)


class _GatedSource(Executor):
    """Build-side source that holds each epoch's chunks until the probe
    side's gate for that epoch opens."""

    identity = "GatedSource"

    def __init__(self, schema: Schema, messages, gates):
        self.schema = schema
        self._messages = list(messages)
        self._gates = gates
        self._epoch_i = 0

    async def execute(self):
        waited = False
        for m in self._messages:
            if not waited:
                await self._gates[self._epoch_i].wait()
                waited = True
            yield m
            await asyncio.sleep(0)
            if isinstance(m, Barrier):
                self._epoch_i += 1
                waited = False


def run_executor_q7(epochs_rows):
    """Drive the REAL q7 executor pipeline over scripted epochs; returns
    per-epoch [(op, row), ...] lists."""
    gates = [asyncio.Event() for _ in range(len(epochs_rows) + 2)]
    probe_msgs, build_msgs = [Barrier.new(1)], [Barrier.new(1)]
    e = 1
    for rows in epochs_rows:
        probe_msgs.append(pchunk(rows))
        build_msgs.append(make_chunk(PRE_SCHEMA,
                                     [(ws, p) for ws, _, p in rows],
                                     capacity=CAP))
        e += 1
        probe_msgs.append(Barrier.new(e))
        build_msgs.append(Barrier.new(e))

    async def drive():
        probe = _ProbeSource(PROBE_SCHEMA, probe_msgs, gates)
        build_pre = _GatedSource(PRE_SCHEMA, build_msgs, gates)
        build = HashAggExecutor(build_pre, [0], [agg_call("max", 1, INT64)],
                                table_capacity=1 << 10, out_capacity=CAP)
        cond = call("equal", col(2, INT64), col(4, INT64))
        join = HashJoinExecutor(
            probe, build, [0], [0], condition=cond,
            key_capacity=1 << 10, bucket_width=16, out_capacity=CAP)
        per_epoch, cur = [], []
        async for m in join.execute():
            from risingwave_tpu.common import StreamChunk
            if isinstance(m, StreamChunk):
                cur.extend(chunk_to_rows(m, join.schema, with_ops=True))
            elif isinstance(m, Barrier):
                per_epoch.append(cur)
                cur = []
        return per_epoch[1:]   # drop the empty first barrier

    return asyncio.run(drive())


# ---------------------------------------------------------------------------
# interval core driver
# ---------------------------------------------------------------------------


def make_core(**kw):
    kw.setdefault("n_buckets", 256)
    kw.setdefault("lane_width", 16)
    return IntervalJoinCore(PROBE_SCHEMA, ts_col=0, val_col=2,
                            window_us=WINDOW, **kw)


def run_core_q7(epochs_rows, core=None, snapshot_at=None):
    """Apply the same epochs through IntervalJoinCore; returns per-epoch
    [(op, row), ...]. ``snapshot_at``: after that epoch index, export the
    state to host numpy and continue on a FRESH core via import_host (the
    checkpoint/recovery cycle)."""
    core = core or make_core()
    apply_c = jax.jit(core.apply_chunk)
    plan = jax.jit(core.flush_plan)
    gather = jax.jit(core.gather_flush, static_argnames=("out_capacity",))
    finish = jax.jit(core.finish_flush)
    state = core.init_state()
    per_epoch = []
    for ei, rows in enumerate(epochs_rows):
        cur = []
        state, out = apply_c(state, pchunk(rows))
        cur.extend(chunk_to_rows(out, core.out_schema, with_ops=True))
        old_emitted = state.emitted_max
        del_m, ins_m, packed = plan(state)
        n_units, ovf, clobber, sawdel = (int(x) for x in np.asarray(packed))
        assert not (ovf or clobber or sawdel)
        lo = 0
        while lo < n_units:
            ch = gather(state, del_m, ins_m, old_emitted, jnp.int64(lo),
                        out_capacity=CAP)
            cur.extend(chunk_to_rows(ch, core.out_schema, with_ops=True))
            lo += CAP
        state = finish(state)
        per_epoch.append(cur)
        if snapshot_at is not None and ei == snapshot_at:
            payload = core.export_host(state)
            core2 = make_core()
            state = core2.import_host(payload)
            apply_c = jax.jit(core2.apply_chunk)
            plan = jax.jit(core2.flush_plan)
            gather = jax.jit(core2.gather_flush,
                             static_argnames=("out_capacity",))
            finish = jax.jit(core2.finish_flush)
    return per_epoch


EPOCHS = [
    # epoch 1: two windows born; window 0 max=9, window 100 max=7
    [(0, 1, 5), (0, 2, 9), (100, 3, 7)],
    # epoch 2: window 0 max unchanged (churn: touched, same max) + a
    # late row equal to the OLD emitted max (probe-time emission, then
    # retracted+re-emitted by the churn flush)
    [(0, 4, 9), (100, 4, 3)],
    # epoch 3: window 0 max RISES → retraction of every price-9 match,
    # new max emitted; window 200 born
    [(0, 5, 12), (200, 6, 4)],
    # epoch 4: quiet window 100 gets a sub-max row (churn only), window
    # 200 tied rows
    [(100, 7, 2), (200, 8, 4), (200, 9, 4)],
]


def test_parity_with_executor_pipeline_under_retraction():
    expected = run_executor_q7(EPOCHS)
    got = run_core_q7(EPOCHS)
    assert len(expected) == len(got)
    for ei, (e_rows, g_rows) in enumerate(zip(expected, got)):
        assert sorted(e_rows) == sorted(g_rows), f"epoch {ei + 1} diverged"
    # retraction actually exercised: epoch 3 must contain DELETEs
    assert any(op == OP_DELETE for op, _ in expected[2])


def test_parity_across_checkpoint_recovery_cycle():
    expected = run_executor_q7(EPOCHS)
    got = run_core_q7(EPOCHS, snapshot_at=1)   # kill+recover mid-run
    for ei, (e_rows, g_rows) in enumerate(zip(expected, got)):
        assert sorted(e_rows) == sorted(g_rows), f"epoch {ei + 1} diverged"


def test_probe_time_emission_against_flushed_max():
    # window flushed with max 9; a later bid at 9 matches at probe time
    per_epoch = run_core_q7([
        [(0, 1, 9)],
        [(0, 2, 9)],
    ])
    # epoch 1: insert of (0,1,9) via flush
    assert (OP_INSERT, (0, 1, 9, 0, 9)) in per_epoch[0]
    # epoch 2 contains the probe-time insert of the late row
    assert (OP_INSERT, (0, 2, 9, 0, 9)) in per_epoch[1]


def test_ring_turnover_reclaims_slots():
    core = make_core(n_buckets=4, lane_width=4)
    apply_c = jax.jit(core.apply_chunk)
    finish = jax.jit(core.finish_flush)
    state = core.init_state()
    # windows 0 and 4*WINDOW map to the same ring slot
    state, _ = apply_c(state, pchunk([(0, 1, 5)]))
    state = finish(state)
    state, _ = apply_c(state, pchunk([(4 * WINDOW, 2, 7)]))
    assert not bool(state.ring_clobber)
    assert int(state.win_id[0]) == 4
    assert int(state.cur_max[0]) == 7       # old window's max was reset
    assert not bool(state.emitted_live[0])  # downstream build row dropped


def test_ring_clobber_of_dirty_slot_is_flagged():
    core = make_core(n_buckets=4, lane_width=4)
    apply_c = jax.jit(core.apply_chunk)
    state = core.init_state()
    # window 0 has an UNFLUSHED delta when window 4 steals its slot
    state, _ = apply_c(state, pchunk([(0, 1, 5)]))
    state, _ = apply_c(state, pchunk([(4 * WINDOW, 2, 7)]))
    assert bool(state.ring_clobber)


def test_probe_delete_sets_sticky_flag():
    core = make_core()
    apply_c = jax.jit(core.apply_chunk)
    state = core.init_state()
    ch = make_chunk(PROBE_SCHEMA, [(0, 1, 5)], ops=[OP_DELETE],
                    capacity=CAP)
    state, _ = apply_c(state, ch)
    assert bool(state.saw_delete)


def test_lane_overflow_sets_sticky_flag():
    core = make_core(lane_width=2)
    apply_c = jax.jit(core.apply_chunk)
    state = core.init_state()
    state, _ = apply_c(state, pchunk([(0, i, i) for i in range(3)]))
    assert bool(state.lane_overflow)


def test_band_filter_restricts_matches():
    # band over the raw ts (col 0 doubles as the band column here):
    # only rows in [win_start, win_start + 50) may match
    core = IntervalJoinCore(PROBE_SCHEMA, ts_col=0, val_col=2,
                            window_us=WINDOW, n_buckets=64, lane_width=8,
                            band_col=0, band_us=50)
    apply_c = jax.jit(core.apply_chunk)
    plan = jax.jit(core.flush_plan)
    gather = jax.jit(core.gather_flush, static_argnames=("out_capacity",))
    state = core.init_state()
    # ts 10 in band; ts 60 (same window, same max price) out of band
    state, _ = apply_c(state, pchunk([(10, 1, 9), (60, 2, 9)]))
    old = state.emitted_max
    del_m, ins_m, packed = plan(state)
    assert int(packed[0]) == 1
    ch = gather(state, del_m, ins_m, old, jnp.int64(0), out_capacity=CAP)
    rows = chunk_to_rows(ch, core.out_schema, with_ops=True)
    assert rows == [(OP_INSERT, (10, 1, 9, 0, 9))]


def test_interval_match_kernel_parity():
    """Pallas (interpret) and jnp formulations are bit-identical."""
    rng = np.random.default_rng(7)
    nb, w = 512, 128
    vals = jnp.asarray(rng.integers(0, 5, (nb, w)), jnp.int64)
    occ = jnp.asarray(rng.random((nb, w)) < 0.7)
    old_max = jnp.asarray(rng.integers(0, 5, nb), jnp.int64)
    new_max = jnp.asarray(rng.integers(0, 5, nb), jnp.int64)
    old_live = jnp.asarray(rng.random(nb) < 0.8)
    new_live = jnp.asarray(rng.random(nb) < 0.8)
    # exercise the 64-bit halves: some values only differ in the high word
    vals = vals + (jnp.asarray(
        rng.integers(0, 2, (nb, w)), jnp.int64) << 33)
    old_max = old_max + (jnp.asarray(
        rng.integers(0, 2, nb), jnp.int64) << 33)
    d0, i0 = interval_match_jnp(vals, occ, old_max, old_live,
                                new_max, new_live)
    d1, i1 = interval_match_pallas(vals, occ, old_max, old_live,
                                   new_max, new_live, interpret=True)
    np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))
    np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
