"""Native C++ row codec: byte-identical to the Python encoders, and the
checkpoint fast path produces the same durable state (task: native runtime
components)."""

import numpy as np
import pytest

from risingwave_tpu.common.row import encode_key, encode_value_row
from risingwave_tpu.common.types import (
    BOOL, DATE, FLOAT32, FLOAT64, GLOBAL_STRING_DICT, INT16, INT32, INT64,
    VARCHAR, Field, Schema, decimal,
)
from risingwave_tpu.native import codec

pytestmark = pytest.mark.skipif(codec() is None,
                                reason="native toolchain unavailable")

TYPES = [INT64, INT32, INT16, BOOL, FLOAT64, FLOAT32, DATE, decimal(2),
         VARCHAR]

ROWS = [
    (42, -7, 3, True, 1.5, -2.25, 9204, 1234, "alpha"),
    (-1, None, -3, False, -0.0, None, None, -505, "with\x00zero"),
    (0, 2**31 - 1, None, None, float("inf"), 1.0, -10, None, ""),
    (2**62, -2**31, -32768, True, -1e300, -1.5, 0, 99, "βeta"),
]


def _columns(rows, types):
    n = len(rows)
    datas, masks = [], []
    for c, t in enumerate(types):
        arr = np.zeros(n, t.np_dtype)
        mask = np.zeros(n, bool)
        for r, row in enumerate(rows):
            if row[c] is not None:
                arr[r] = t.to_physical(row[c])
                mask[r] = True
        datas.append(arr)
        masks.append(mask)
    return datas, masks


def _physical(row, types):
    return tuple(None if v is None else t.to_physical(v)
                 for v, t in zip(row, types))


class TestByteIdentical:
    def test_value_rows_match_python(self):
        datas, masks = _columns(ROWS, TYPES)
        got = codec().encode_value_rows(datas, masks, TYPES,
                                        np.arange(len(ROWS)))
        for r, row in enumerate(ROWS):
            expect = encode_value_row(_physical(row, TYPES), TYPES)
            assert got[r] == expect, f"row {r} value encoding differs"

    def test_keys_match_python(self):
        datas, masks = _columns(ROWS, TYPES)
        got = codec().encode_keys(datas, masks, TYPES, np.arange(len(ROWS)))
        for r, row in enumerate(ROWS):
            expect = encode_key(_physical(row, TYPES), TYPES)
            assert got[r] == expect, f"row {r} key encoding differs"

    def test_key_order_preserved(self):
        vals = [(-(2**40),), (-5,), (0,), (3,), (2**50,), (None,)]
        datas, masks = _columns(vals, [INT64])
        keys = codec().encode_keys(datas, masks, [INT64],
                                   np.arange(len(vals)))
        order = sorted(range(len(vals)), key=lambda i: keys[i])
        # NULL sorts first, then numeric order
        assert order == [5, 0, 1, 2, 3, 4]

    def test_row_subset_selection(self):
        datas, masks = _columns(ROWS, TYPES)
        got = codec().encode_value_rows(datas, masks, TYPES,
                                        np.array([2, 0]))
        assert got[0] == encode_value_row(_physical(ROWS[2], TYPES), TYPES)
        assert got[1] == encode_value_row(_physical(ROWS[0], TYPES), TYPES)


class TestCheckpointPath:
    def test_rs_checkpoint_native_equals_python(self, monkeypatch):
        """The same dirty row-set checkpointed through the native path and
        the Python path must produce identical durable KV state."""
        import jax.numpy as jnp
        from risingwave_tpu.common.chunk import OP_DELETE, make_chunk
        from risingwave_tpu.ops.row_set import rs_apply_chunk, rs_checkpoint
        from risingwave_tpu.ops.row_set import rs_new
        from risingwave_tpu.storage.state_store import MemoryStateStore
        from risingwave_tpu.storage.state_table import StateTable

        schema = Schema((Field("k", INT64), Field("s", VARCHAR),
                         Field("x", FLOAT64)))
        rows = [(1, "a", 1.5), (2, "b", None), (3, None, -2.0),
                (4, "dd", 0.25)]

        def run(disable_native):
            import risingwave_tpu.native as native_mod
            store = MemoryStateStore()
            st = StateTable(store, 1, schema, [0])
            rs = rs_new([INT64], [INT64, VARCHAR, FLOAT64], 64)
            chunk = make_chunk(schema, rows, capacity=8)
            rs, _, _ = rs_apply_chunk(rs, chunk, (0,))
            dchunk = make_chunk(schema, [rows[1]], ops=[OP_DELETE],
                                capacity=2)
            rs, _, _ = rs_apply_chunk(rs, dchunk, (0,))
            if disable_native:
                monkeypatch.setattr(native_mod, "_lib", None)
                monkeypatch.setattr(native_mod, "_tried", True)
            else:
                monkeypatch.setattr(native_mod, "_tried", False)
            rs_checkpoint(rs, st, epoch=1)
            store.commit(1)
            return dict(store.iter_table(1))

        native_kv = run(False)
        python_kv = run(True)
        assert native_kv == python_kv
        assert len(native_kv) == 3
