"""Expression-engine tests (counterpart of reference vector_op unit tests)."""

import jax
import numpy as np

from risingwave_tpu.common import (
    BOOL, FLOAT64, INT64, TIMESTAMP, Schema, chunk_to_rows, make_chunk,
)
from risingwave_tpu.expr import call, cast, col, Literal
from risingwave_tpu.common.chunk import Column

SCHEMA = Schema.of(("a", INT64), ("b", INT64), ("f", FLOAT64), ("flag", BOOL))


def rows_of(column, type_, chunk):
    out = []
    data = np.asarray(column.data)
    mask = np.asarray(column.mask)
    vis = np.asarray(chunk.vis)
    for i in range(len(data)):
        if vis[i]:
            out.append(type_.to_python(data[i]) if mask[i] else None)
    return out


def test_arith_and_nulls():
    chunk = make_chunk(SCHEMA, [(1, 10, 1.5, True), (2, None, 2.0, False), (3, 30, None, None)], capacity=4)
    e = col(0, INT64) + col(1, INT64)
    out = e.eval(chunk)
    assert rows_of(out, INT64, chunk) == [11, None, 33]
    prod = col(2, FLOAT64) * 2.0
    assert rows_of(prod.eval(chunk), FLOAT64, chunk) == [3.0, 4.0, None]


def test_divide_by_zero_is_null():
    chunk = make_chunk(SCHEMA, [(10, 2, 0.0, True), (10, 0, 0.0, True)], capacity=2)
    out = (col(0, INT64) / col(1, INT64)).eval(chunk)
    assert rows_of(out, INT64, chunk) == [5, None]


def test_comparison_and_kleene_logic():
    chunk = make_chunk(SCHEMA, [(1, 2, 0.0, True), (2, 2, 0.0, None), (3, None, 0.0, False)], capacity=4)
    lt = col(0, INT64) < col(1, INT64)
    assert rows_of(lt.eval(chunk), BOOL, chunk) == [True, False, None]
    # Kleene: NULL AND FALSE = FALSE, NULL OR TRUE = TRUE
    e_and = call("and", col(3, BOOL), Literal(False, BOOL))
    assert rows_of(e_and.eval(chunk), BOOL, chunk) == [False, False, False]
    e_or = call("or", col(3, BOOL), Literal(True, BOOL))
    assert rows_of(e_or.eval(chunk), BOOL, chunk) == [True, True, True]
    e_and2 = call("and", col(3, BOOL), Literal(True, BOOL))
    assert rows_of(e_and2.eval(chunk), BOOL, chunk) == [True, None, False]


def test_case_coalesce_isnull():
    chunk = make_chunk(SCHEMA, [(1, None, 1.0, True), (2, 20, 2.0, False)], capacity=2)
    coal = call("coalesce", col(1, INT64), col(0, INT64))
    assert rows_of(coal.eval(chunk), INT64, chunk) == [1, 20]
    isn = call("is_null", col(1, INT64))
    assert rows_of(isn.eval(chunk), BOOL, chunk) == [True, False]
    case = call("case", call("is_null", col(1, INT64)), Literal(-1, INT64), col(1, INT64))
    assert rows_of(case.eval(chunk), INT64, chunk) == [-1, 20]


def test_cast_and_tumble():
    sch = Schema.of(("ts", TIMESTAMP),)
    chunk = make_chunk(sch, [(10_500_000,), (19_999_999,), (20_000_000,)], capacity=4)
    win = call("tumble_start", col(0, TIMESTAMP), Literal(10_000_000, INT64))
    assert rows_of(win.eval(chunk), TIMESTAMP, chunk) == [10_000_000, 10_000_000, 20_000_000]
    f = cast(col(0, TIMESTAMP), FLOAT64)
    assert rows_of(f.eval(chunk), FLOAT64, chunk)[0] == 10_500_000.0


def test_exprs_fuse_under_jit():
    chunk = make_chunk(SCHEMA, [(i, i * 2, float(i), True) for i in range(4)], capacity=4)
    e = (col(0, INT64) + col(1, INT64)) * 3

    @jax.jit
    def step(c):
        out = e.eval(c)
        return c.with_columns([Column(out.data, out.mask)])

    got = step(chunk)
    assert rows_of(got.columns[0], INT64, got) == [0, 9, 18, 27]
