"""DELETE / UPDATE DML (reference: batch Delete/Update executors through
the DmlManager rendezvous): retractions flow through MVs incrementally."""

import pytest

from risingwave_tpu.frontend import Session


def _setup():
    s = Session()
    s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, g BIGINT, v BIGINT)")
    s.run_sql("INSERT INTO t VALUES (1, 0, 10), (2, 1, 20), (3, 0, 30), "
              "(4, 1, 40)")
    s.flush()
    return s


class TestDelete:
    def test_delete_where(self):
        s = _setup()
        out = s.run_sql("DELETE FROM t WHERE v > 25")
        assert out == [("DELETE", 2)]
        s.flush()
        assert sorted(s.run_sql("SELECT k FROM t")) == [(1,), (2,)]

    def test_delete_all_and_mv_retracts(self):
        s = _setup()
        s.run_sql("CREATE MATERIALIZED VIEW m AS "
                  "SELECT g, sum(v) AS sv FROM t GROUP BY g")
        s.flush()
        assert sorted(s.mv_rows("m")) == [(0, 40), (1, 60)]
        s.run_sql("DELETE FROM t WHERE g = 0")
        s.flush()
        assert sorted(s.mv_rows("m")) == [(1, 60)]
        s.run_sql("DELETE FROM t")
        s.flush()
        assert s.mv_rows("m") == []
        assert s.run_sql("SELECT k FROM t") == []

    def test_requires_pk_and_not_append_only(self):
        s = Session()
        s.run_sql("CREATE TABLE noz (a BIGINT)")      # hidden row-id pk
        with pytest.raises(Exception, match="PRIMARY KEY"):
            s.run_sql("DELETE FROM noz")
        s.run_sql("CREATE TABLE ao (a BIGINT PRIMARY KEY) "
                  "WITH (appendonly = 'true')")
        with pytest.raises(Exception, match="APPEND ONLY"):
            s.run_sql("DELETE FROM ao")


class TestUpdate:
    def test_update_values_and_mv(self):
        s = _setup()
        s.run_sql("CREATE MATERIALIZED VIEW m AS "
                  "SELECT g, sum(v) AS sv FROM t GROUP BY g")
        s.flush()
        out = s.run_sql("UPDATE t SET v = v + 100 WHERE g = 0")
        assert out == [("UPDATE", 2)]
        s.flush()
        assert sorted(s.run_sql("SELECT k, v FROM t")) == [
            (1, 110), (2, 20), (3, 130), (4, 40)]
        assert sorted(s.mv_rows("m")) == [(0, 240), (1, 60)]

    def test_update_pk_column(self):
        s = _setup()
        s.run_sql("UPDATE t SET k = k + 100 WHERE k = 1")
        s.flush()
        assert sorted(r[0] for r in s.run_sql("SELECT k FROM t")) == \
            [2, 3, 4, 101]

    def test_update_multiple_columns_and_unseen_insert(self):
        s = _setup()
        # an INSERT staged in the same epoch is visible to the UPDATE
        s.run_sql("INSERT INTO t VALUES (5, 0, 50)")
        s.run_sql("UPDATE t SET g = 9, v = 0 WHERE k = 5")
        s.flush()
        rows = dict((r[0], (r[1], r[2])) for r in
                    s.run_sql("SELECT k, g, v FROM t"))
        assert rows[5] == (9, 0)


class TestPkUpdateCollisions:
    def test_shift_all_keys(self):
        s = Session()
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, v BIGINT)")
        s.run_sql("INSERT INTO t VALUES (1, 10), (2, 20)")
        s.flush()
        s.run_sql("UPDATE t SET k = k + 1")
        s.flush()
        assert sorted(s.run_sql("SELECT k, v FROM t")) == [(2, 10), (3, 20)]

    def test_collision_with_existing_row_rejected(self):
        s = Session()
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, v BIGINT)")
        s.run_sql("INSERT INTO t VALUES (1, 10), (2, 20)")
        s.flush()
        with pytest.raises(Exception, match="collides"):
            s.run_sql("UPDATE t SET k = 2 WHERE k = 1")
        s.flush()
        assert sorted(s.run_sql("SELECT k, v FROM t")) == [(1, 10), (2, 20)]

    def test_duplicate_within_update_rejected(self):
        s = Session()
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, v BIGINT)")
        s.run_sql("INSERT INTO t VALUES (1, 10), (2, 20)")
        s.flush()
        with pytest.raises(Exception, match="duplicate key"):
            s.run_sql("UPDATE t SET k = 7")
