"""CH-benCHmark-style mixed workload (BASELINE.md config 5): OLTP-ish
DML churn against TPC-H-shaped tables while analytic MVs (join + agg +
window + top-n) stay incrementally correct — single-chip and sharded over
the virtual device mesh. Expected values recomputed by host models.
Reference workload shape: /root/reference e2e_test/ch_benchmark/."""

import random

import jax
import numpy as np
import pytest

from risingwave_tpu.frontend import Session
from risingwave_tpu.frontend.build import BuildConfig


def _mesh(n):
    from jax.sharding import Mesh
    devs = jax.devices()
    if len(devs) < n:
        pytest.skip(f"needs {n} devices")
    return Mesh(np.array(devs[:n]), ("shard",))


DDL = [
    """CREATE TABLE customer (c_id BIGINT PRIMARY KEY, c_state VARCHAR,
       c_balance BIGINT)""",
    """CREATE TABLE orders (o_id BIGINT PRIMARY KEY, o_c_id BIGINT,
       o_carrier BIGINT)""",
    """CREATE TABLE order_line (ol_o_id BIGINT, ol_number BIGINT,
       ol_amount BIGINT, PRIMARY KEY (ol_o_id, ol_number))""",
]

MVS = [
    # revenue per customer state (3-way join + group agg)
    """CREATE MATERIALIZED VIEW rev_by_state AS
       SELECT c_state, sum(ol_amount) AS revenue
       FROM customer, orders, order_line
       WHERE c_id = o_c_id AND o_id = ol_o_id
       GROUP BY c_state""",
    # top spender ranking (window over agg output via subquery)
    """CREATE MATERIALIZED VIEW order_totals AS
       SELECT ol_o_id, sum(ol_amount) AS total
       FROM order_line GROUP BY ol_o_id""",
    """CREATE MATERIALIZED VIEW top_orders AS
       SELECT ol_o_id, total FROM order_totals
       ORDER BY total DESC LIMIT 3""",
]


def _host_models(customers, orders, lines):
    rev = {}
    for c_id, state, _ in customers:
        for o_id, o_c, _ in orders:
            if o_c != c_id:
                continue
            for lo, ln, amt in lines:
                if lo == o_id:
                    rev[state] = rev.get(state, 0) + amt
    totals = {}
    for lo, ln, amt in lines:
        totals[lo] = totals.get(lo, 0) + amt
    top = sorted(totals.items(), key=lambda kv: (-kv[1], kv[0]))[:3]
    return rev, totals, set(top)


def _run(session_config):
    rng = random.Random(5)
    s = Session(config=session_config)
    for d in DDL:
        s.run_sql(d)
    for m in MVS:
        s.run_sql(m)

    customers, orders, lines = [], [], []
    states = ["CA", "OR", "TX"]
    oid = 0
    for step in range(6):
        # OLTP-ish churn: new customers, orders, order lines every "txn"
        c_id = step
        st = states[rng.randint(0, 2)]
        customers.append((c_id, st, rng.randint(0, 999)))
        s.run_sql(f"INSERT INTO customer VALUES ({c_id}, '{st}', "
                  f"{customers[-1][2]})")
        for _ in range(rng.randint(1, 2)):
            oid += 1
            orders.append((oid, c_id, rng.randint(1, 9)))
            s.run_sql(f"INSERT INTO orders VALUES ({oid}, {c_id}, "
                      f"{orders[-1][2]})")
            for ln in range(1, rng.randint(2, 4)):
                amt = rng.randint(10, 500)
                lines.append((oid, ln, amt))
                s.run_sql("INSERT INTO order_line VALUES "
                          f"({oid}, {ln}, {amt})")
        s.flush()

        rev, totals, top = _host_models(customers, orders, lines)
        got_rev = {r[0]: r[1] for r in s.mv_rows("rev_by_state")}
        assert got_rev == rev, f"step {step}: {got_rev} != {rev}"
        got_top = {(r[0], r[1]) for r in s.mv_rows("top_orders")}
        assert got_top == top, f"step {step}: {got_top} != {top}"
    return s


class TestChBench:
    def test_mixed_workload_single_chip(self):
        _run(None)

    @pytest.mark.slow
    def test_mixed_workload_sharded_mesh(self):
        """The same workload with joins/aggs sharded over a 4-device mesh
        (BASELINE config 5's scale-out shape)."""
        _run(BuildConfig(mesh=_mesh(4)))
