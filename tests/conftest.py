"""Test harness: run everything on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding correctness is
validated on XLA's host platform with 8 forced devices (the same trick the
driver's dryrun uses). Must run before jax is imported anywhere.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
