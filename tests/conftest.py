"""Test harness: run everything on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding correctness is
validated on XLA's host platform with 8 forced devices (the same trick the
driver's dryrun uses). Must run before jax is imported anywhere.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Persistent XLA compilation cache: pytest re-runs recompile hundreds of
# kernels otherwise; cache survives across processes and cuts suite time ~10x.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_pytest_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")

# The agent image's sitecustomize registers the real-TPU 'axon' PJRT plugin
# at interpreter startup (before this conftest runs), importing jax with
# jax_platforms pinned from the then-current env.  The env mutations above
# are therefore too late for THIS process — force the config directly.
# Backends are not yet initialized at conftest time, so this takes effect.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running; excluded from the tier-1 run")
