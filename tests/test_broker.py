"""Broker-shaped source/sink + Avro parser (VERDICT r4 item 10; reference:
src/connector/src/source/base.rs:295-340 Kafka splits,
src/connector/src/parser/avro/, src/connector/src/sink/kafka.rs)."""

import json
import os
import tempfile

import pytest

from risingwave_tpu.connector.avro import AvroCodec
from risingwave_tpu.connector.broker import (
    BrokerClient, BrokerServer, BrokerSourceReader,
)
from risingwave_tpu.frontend import Session


AVRO_SCHEMA = json.dumps({
    "type": "record", "name": "bid",
    "fields": [
        {"name": "auction", "type": "long"},
        {"name": "price", "type": ["null", "long"]},
        {"name": "channel", "type": "string"},
        {"name": "ok", "type": "boolean"},
        {"name": "score", "type": "double"},
    ],
})


def test_avro_roundtrip():
    codec = AvroCodec(AVRO_SCHEMA)
    recs = [
        {"auction": 7, "price": 1200, "channel": "web", "ok": True,
         "score": 2.5},
        {"auction": -3, "price": None, "channel": "", "ok": False,
         "score": -0.125},
    ]
    for r in recs:
        assert codec.decode(codec.encode(r)) == r
    # zero-leading datum (auction=0) must NOT be mistaken for framing
    zero = {"auction": 0, "price": 0, "channel": "ch0", "ok": True,
            "score": 0.0}
    assert codec.decode(codec.encode(zero)) == zero
    # Confluent framing is explicit, declared per codec
    confluent = AvroCodec(AVRO_SCHEMA, framing="confluent")
    framed = b"\x00\x00\x00\x00\x07" + codec.encode(recs[0])
    assert confluent.decode(framed) == recs[0]
    with pytest.raises(Exception):
        codec.decode(b"\xff\x01")     # truncated garbage fails loudly


def test_broker_server_protocol_and_reader():
    srv = BrokerServer(n_partitions=2).start()
    try:
        cl = BrokerClient(srv.address)
        assert cl.n_partitions("t") == 2
        assert cl.publish("t", 0, b'{"a": 1}') == 0
        assert cl.publish("t", 0, b'{"a": 2}') == 1
        assert cl.publish("t", 1, b'{"a": 3}') == 0
        assert cl.fetch("t", 0, 0, 10) == [b'{"a": 1}', b'{"a": 2}']
        assert cl.fetch("t", 0, 2, 10) == []
        cl.close()

        from risingwave_tpu.common import chunk_to_rows
        from risingwave_tpu.common.types import Field, INT64, Schema
        schema = Schema((Field("a", INT64),))
        rd = BrokerSourceReader(schema, srv.address, "t",
                                rows_per_chunk=8)
        got = []
        while True:
            ch = rd.next_chunk()
            if ch is None:
                break
            got.extend(chunk_to_rows(ch, schema))
        assert sorted(got) == [(1,), (2,), (3,)]
        assert rd.offsets == {"t-0": 2, "t-1": 1}
        # deterministic seek: replay of [0, ..) yields identical rows
        rd.seek({"t-0": 0, "t-1": 0})
        replay = []
        while True:
            ch = rd.next_chunk()
            if ch is None:
                break
            replay.extend(chunk_to_rows(ch, schema))
        assert sorted(replay) == sorted(got)
        rd.close()
    finally:
        srv.close()


def test_broker_source_e2e_with_crash_resume():
    """CREATE SOURCE over the broker; kill the session; publish more;
    a recovered session must resume from the checkpointed offsets —
    no duplicates, no gaps."""
    with tempfile.TemporaryDirectory() as d:
        srv = BrokerServer(n_partitions=2).start()
        try:
            cl = BrokerClient(srv.address)
            for i in range(6):
                cl.publish("bids", i % 2,
                           json.dumps({"auction": i, "price": 100 + i})
                           .encode())
            data = os.path.join(d, "data")
            s = Session(data_dir=data)
            s.run_sql(f"""CREATE SOURCE bid (auction BIGINT, price BIGINT)
                WITH (connector = 'broker',
                      'broker.address' = '{srv.address}',
                      topic = 'bids')""")
            s.run_sql("CREATE MATERIALIZED VIEW m AS "
                      "SELECT auction, price FROM bid")
            s.tick()
            s.tick()       # two partitions: a tick drains one chunk each
            s.run_sql("FLUSH")
            assert sorted(s.mv_rows("m")) == [
                (i, 100 + i) for i in range(6)]
            s.close()

            # while "down": six more events
            for i in range(6, 12):
                cl.publish("bids", i % 2,
                           json.dumps({"auction": i, "price": 100 + i})
                           .encode())
            s2 = Session(data_dir=data)
            s2.tick()
            s2.tick()
            assert sorted(s2.mv_rows("m")) == [
                (i, 100 + i) for i in range(12)]
            s2.close()
            cl.close()
        finally:
            srv.close()


def test_broker_avro_source():
    srv = BrokerServer(n_partitions=1).start()
    try:
        codec = AvroCodec(AVRO_SCHEMA)
        cl = BrokerClient(srv.address)
        for i in range(4):
            cl.publish("av", 0, codec.encode({
                "auction": i, "price": None if i == 2 else i * 10,
                "channel": f"ch{i}", "ok": i % 2 == 0,
                "score": i / 2}))
        cl.publish("av", 0, b"\xff garbage \xff")   # dropped, not fatal
        s = Session()
        s.run_sql(f"""CREATE SOURCE av (auction BIGINT, price BIGINT,
                channel VARCHAR, ok BOOLEAN, score DOUBLE)
            WITH (connector = 'broker',
                  'broker.address' = '{srv.address}',
                  topic = 'av', format = 'avro',
                  'avro.schema' = '{AVRO_SCHEMA.replace(chr(39), "")}')""")
        s.run_sql("CREATE MATERIALIZED VIEW m AS SELECT auction, price, "
                  "channel, ok, score FROM av")
        s.tick()
        rows = sorted(s.mv_rows("m"))
        assert rows == [
            (0, 0, "ch0", True, 0.0),
            (1, 10, "ch1", False, 0.5),
            (2, None, "ch2", True, 1.0),
            (3, 30, "ch3", False, 1.5),
        ]
        s.close()
    finally:
        srv.close()


def test_broker_sink_changelog():
    """MV changelog delivered to a broker topic as JSON with __op."""
    srv = BrokerServer(n_partitions=1).start()
    try:
        s = Session()
        s.run_sql("CREATE TABLE t (id BIGINT PRIMARY KEY, v BIGINT)")
        s.run_sql("CREATE MATERIALIZED VIEW m AS "
                  "SELECT id, v FROM t WHERE v > 10")
        s.run_sql(f"""CREATE SINK snk FROM m
            WITH (connector = 'broker',
                  'broker.address' = '{srv.address}',
                  topic = 'out')""")
        s.run_sql("INSERT INTO t VALUES (1, 5), (2, 20)")
        s.tick()
        s.run_sql("DELETE FROM t WHERE id = 2")
        s.tick()
        s.close()
        cl = BrokerClient(srv.address)
        msgs = [json.loads(m) for m in cl.fetch("out", 0, 0, 100)]
        cl.close()
        inserts = [m for m in msgs if m["__op"] == "insert"]
        deletes = [m for m in msgs if m["__op"] == "delete"]
        assert {(m["id"], m["v"]) for m in inserts} == {(2, 20)}
        assert {(m["id"], m["v"]) for m in deletes} == {(2, 20)}
    finally:
        srv.close()


def _restart(srv):
    """Bounce a broker on the SAME address (durable segments reload)."""
    host, port, nparts, data_dir = (srv.host, srv.port, srv.n_partitions,
                                    srv.data_dir)
    srv.close()
    return BrokerServer(host=host, port=port, n_partitions=nparts,
                        data_dir=data_dir).start()


def test_client_survives_broker_restart_fetch_and_meta():
    """ISSUE 3 satellite: a socket error no longer leaves the client
    permanently dead — commands transparently reconnect with backoff."""
    with tempfile.TemporaryDirectory() as d:
        srv = BrokerServer(n_partitions=2, data_dir=d).start()
        cl = BrokerClient(srv.address)
        cl.publish("t", 0, b"a")
        cl.publish("t", 1, b"b")
        srv = _restart(srv)
        try:
            # same client object: fetch/meta reconnect and serve
            assert cl.fetch("t", 0, 0, 10) == [b"a"]
            assert cl.n_partitions("t") == 2
            assert cl.publish("t", 0, b"c") == 1
            assert cl.fetch("t", 0, 0, 10) == [b"a", b"c"]
            cl.close()
        finally:
            srv.close()


def test_publish_replay_deduped_by_offset_after_restart():
    """A publish batch interrupted by a broker bounce is finished without
    duplicating the messages whose acks were lost (offset-position
    dedup over LEN)."""
    with tempfile.TemporaryDirectory() as d:
        srv = BrokerServer(n_partitions=1, data_dir=d).start()
        cl = BrokerClient(srv.address)
        assert cl.publish_many("t", 0, [b"m0", b"m1"]) == 1
        # bounce between batches: the client's dedup cursor (next offset
        # = 2) sees both messages landed and resends nothing
        srv = _restart(srv)
        try:
            assert cl.publish_many("t", 0, [b"m2", b"m3"]) == 3
            assert cl.fetch("t", 0, 0, 10) == [b"m0", b"m1", b"m2", b"m3"]
            cl.close()
        finally:
            srv.close()


def test_source_reader_survives_broker_restart(tmp_path):
    """BrokerSourceReader keeps consuming across a broker restart: the
    reconnecting client re-fetches at the reader's tracked offsets — no
    duplicates, no gaps."""
    from risingwave_tpu.common import chunk_to_rows
    from risingwave_tpu.common.types import Field, INT64, Schema
    srv = BrokerServer(n_partitions=1,
                       data_dir=str(tmp_path / "b")).start()
    cl = BrokerClient(srv.address)
    for i in range(3):
        cl.publish("t", 0, json.dumps({"a": i}).encode())
    schema = Schema((Field("a", INT64),))
    rd = BrokerSourceReader(schema, srv.address, "t", rows_per_chunk=8)
    got = []
    ch = rd.next_chunk()
    got.extend(chunk_to_rows(ch, schema))
    assert got == [(0,), (1,), (2,)]

    srv = _restart(srv)
    try:
        assert rd.next_chunk() is None      # nothing new; offsets intact
        for i in range(3, 6):
            cl.publish("t", 0, json.dumps({"a": i}).encode())
        ch = rd.next_chunk()
        got.extend(chunk_to_rows(ch, schema))
        assert got == [(i,) for i in range(6)]
        assert rd.offsets == {"t-0": 6}
        rd.close()
        cl.close()
    finally:
        srv.close()


def test_error_reply_mid_batch_does_not_desync_client():
    """A broker-side ERR inside a pipelined PUB batch leaves unread
    replies buffered; the client must drop the connection so later
    commands don't consume stale replies."""
    srv = BrokerServer(n_partitions=1).start()
    try:
        cl = BrokerClient(srv.address)
        # pre-anchor the cursor so the batch goes straight to the
        # pipelined path against a partition the server rejects
        cl._next_off[("t", 5)] = 0
        with pytest.raises(RuntimeError, match="broker error"):
            cl.publish_many("t", 5, [b"a", b"b", b"c"])
        # the same client object stays reply-aligned afterwards
        assert cl.publish("t", 0, b"x") == 0
        assert cl.fetch("t", 0, 0, 10) == [b"x"]
        assert cl.n_partitions("t") == 1
        cl.close()
    finally:
        srv.close()


def test_broker_sink_retry_does_not_duplicate_landed_prefix():
    """A delivery attempt whose messages LANDED but whose acks were lost
    must not republish on the executor's retry: the sink skips the
    landed prefix via the client's offset cursor."""
    from risingwave_tpu.common.types import Field, INT64, Schema
    from risingwave_tpu.connector.sinks import BrokerSink
    srv = BrokerServer(n_partitions=1).start()
    try:
        schema = Schema((Field("k", INT64),))
        snk = BrokerSink(srv.address, "out", schema)
        rows = [(0, (1,)), (0, (2,))]
        orig = snk.client.publish_many
        state = {"calls": 0}

        def acks_lost(topic, part, payloads):
            out = orig(topic, part, payloads)
            state["calls"] += 1
            if state["calls"] == 1:
                raise ConnectionError("acks lost after landing")
            return out

        snk.client.publish_many = acks_lost
        with pytest.raises(ConnectionError):
            snk.write_rows(rows)
        # the executor's retry loop rolls back then replays the batch
        snk.truncate_to(0)
        snk.write_rows(rows)
        snk.flush()
        cl = BrokerClient(srv.address)
        msgs = [json.loads(m) for m in cl.fetch("out", 0, 0, 100)]
        cl.close()
        assert [m["k"] for m in msgs] == [1, 2]     # exactly once
        snk.close()
    finally:
        srv.close()


def test_broker_durable_segments_survive_restart():
    with tempfile.TemporaryDirectory() as d:
        srv = BrokerServer(n_partitions=1, data_dir=d).start()
        cl = BrokerClient(srv.address)
        cl.publish("t", 0, b"one")
        cl.publish("t", 0, b"two")
        cl.close()
        srv.close()
        srv2 = BrokerServer(n_partitions=1, data_dir=d).start()
        try:
            cl = BrokerClient(srv2.address)
            assert cl.fetch("t", 0, 0, 10) == [b"one", b"two"]
            cl.close()
        finally:
            srv2.close()
