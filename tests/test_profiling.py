"""Device profiling plane (common/profiling.py, ISSUE 12): per-dispatch
cost/memory telemetry keyed by the dispatch-counter qualnames, the
cluster-wide HBM ledger, AOT roofline analysis, and the bench trend
folding — plus the wiring surfaces (Session.metrics()["profiling"] /
["dispatch"], Prometheus, ctl profile/bench)."""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import pytest

from risingwave_tpu.common.dispatch_count import count_dispatches
from risingwave_tpu.common.profiling import (
    GLOBAL_PROFILER, aot_analysis, bench_trend, hbm_ledger,
    load_bench_history, profile_dispatch, render_roofline_table,
    render_trend_table, roofline_report,
)
from risingwave_tpu.common.tracing import CAT_DISPATCH, GLOBAL_TRACE

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
Q5_EPOCH = "fused_source_agg_epoch.<locals>.epoch"
Q7_EPOCH = "fused_source_join_epoch.<locals>.epoch"


@pytest.fixture(autouse=True)
def _fresh_profiler():
    enabled, span_min = GLOBAL_PROFILER.enabled, GLOBAL_PROFILER.span_min_ms
    GLOBAL_PROFILER.reset()
    GLOBAL_PROFILER.enabled = True
    GLOBAL_PROFILER.span_min_ms = 0.0
    GLOBAL_PROFILER.epoch = None
    yield
    GLOBAL_PROFILER.reset()
    GLOBAL_PROFILER.enabled = enabled
    GLOBAL_PROFILER.span_min_ms = span_min


# ---------------------------------------------------------------------------
# DispatchProfiler core
# ---------------------------------------------------------------------------


def test_wrap_records_calls_seconds_and_compiles():
    f = profile_dispatch(jax.jit(lambda x: x * 2 + 1), "unit.f")
    x = jnp.arange(8.0)
    for _ in range(3):
        f(x)
    rec = GLOBAL_PROFILER.snapshot()["unit.f"]
    assert rec["calls"] == 3
    assert rec["total_s"] > 0 and rec["max_ms"] >= rec["last_ms"]
    # first call traced + compiled; the two cache hits did not
    assert rec["compiles"] == 1 and rec["compile_s"] > 0
    assert GLOBAL_PROFILER.counts() == {"unit.f": 3}


def test_recompile_detected_on_new_shape():
    f = profile_dispatch(jax.jit(lambda x: x + 1), "unit.reshape")
    f(jnp.arange(4.0))
    f(jnp.arange(4.0))
    assert GLOBAL_PROFILER.snapshot()["unit.reshape"]["compiles"] == 1
    f(jnp.arange(16.0))         # new shape -> jit cache miss
    assert GLOBAL_PROFILER.snapshot()["unit.reshape"]["compiles"] == 2


def test_disabled_profiler_is_passthrough():
    GLOBAL_PROFILER.enabled = False
    f = profile_dispatch(jax.jit(lambda x: x - 1), "unit.off")
    assert float(f(jnp.float32(3.0))) == 2.0
    assert "unit.off" not in GLOBAL_PROFILER.counts()


def test_dispatch_spans_land_in_trace_ring_with_epoch_tag():
    GLOBAL_TRACE.clear()
    GLOBAL_PROFILER.epoch = 7
    f = profile_dispatch(jax.jit(lambda x: x * x), "unit.span")
    f(jnp.arange(4.0))
    spans = [s for s in GLOBAL_TRACE.snapshot() if s.cat == CAT_DISPATCH]
    assert spans and spans[-1].name == "unit.span"
    assert spans[-1].epoch == 7 and spans[-1].tid == "dispatch"
    # span_min_ms filters sub-threshold dispatches out of the ring
    GLOBAL_TRACE.clear()
    GLOBAL_PROFILER.span_min_ms = 10_000.0
    f(jnp.arange(4.0))
    assert not [s for s in GLOBAL_TRACE.snapshot()
                if s.cat == CAT_DISPATCH]
    assert GLOBAL_PROFILER.counts()["unit.span"] == 2   # still counted


def test_aot_analysis_flops_bytes_memory():
    f = profile_dispatch(jax.jit(lambda a, b: a @ b), "unit.mm")
    a = jnp.ones((64, 64), jnp.float32)
    f(a, a)
    out = GLOBAL_PROFILER.analyze("unit.mm")["unit.mm"]
    # 64^3 mults + 64^2*63 adds; XLA reports 2*64^3-ish flops
    assert out["cost"]["flops"] >= 2 * 64 * 64 * 63
    assert out["cost"]["bytes_accessed"] >= 3 * 64 * 64 * 4
    assert out["memory"]["arg_bytes"] == 2 * 64 * 64 * 4
    assert out["memory"]["out_bytes"] == 64 * 64 * 4
    # cached: a second analyze() does not error and returns the same
    assert GLOBAL_PROFILER.analyze("unit.mm")["unit.mm"] is out
    # the snapshot carries the analysis once computed
    assert GLOBAL_PROFILER.snapshot()["unit.mm"]["cost"] == out["cost"]


def test_aot_analysis_direct_with_avals():
    jitted = jax.jit(lambda x: jnp.sum(x * 2.0))
    out = aot_analysis(jitted, jax.ShapeDtypeStruct((128,), jnp.float32))
    assert out["cost"]["flops"] > 0
    assert out["memory"]["arg_bytes"] == 128 * 4


# ---------------------------------------------------------------------------
# the acceptance invariant: profiling ON adds ZERO dispatches to the
# fused q5/q7 single-dispatch epochs
# ---------------------------------------------------------------------------


def _q5_fused(cap=128):
    from risingwave_tpu.common import INT64, TIMESTAMP
    from risingwave_tpu.connector import NexmarkConfig
    from risingwave_tpu.connector.nexmark import DeviceBidGenerator
    from risingwave_tpu.expr import Literal, call, col
    from risingwave_tpu.expr.agg import count_star
    from risingwave_tpu.ops.fused_epoch import fused_source_agg_epoch
    from risingwave_tpu.ops.grouped_agg import AggCore

    gen = DeviceBidGenerator(NexmarkConfig(chunk_capacity=cap))
    exprs = [call("tumble_start", col(5, TIMESTAMP),
                  Literal(10_000_000, INT64)), col(0, INT64)]
    core = AggCore((INT64, INT64), (0, 1), [count_star()],
                   table_capacity=1 << 12, out_capacity=cap)
    return fused_source_agg_epoch(gen.chunk_fn(), exprs, core, cap), core


def _q7_fused(cap=128):
    from risingwave_tpu.common import INT64, TIMESTAMP
    from risingwave_tpu.common.types import Field, Schema
    from risingwave_tpu.connector import NexmarkConfig
    from risingwave_tpu.connector.nexmark import DeviceBidGenerator
    from risingwave_tpu.expr import Literal, call, col
    from risingwave_tpu.ops.fused_epoch import fused_source_join_epoch
    from risingwave_tpu.ops.interval_join import IntervalJoinCore

    gen = DeviceBidGenerator(NexmarkConfig(chunk_capacity=cap))
    exprs = [call("tumble_start", col(5, TIMESTAMP),
                  Literal(10_000_000, INT64)),
             col(0, INT64), col(2, INT64)]
    schema = Schema((Field("window_start", TIMESTAMP),
                     Field("auction", INT64), Field("price", INT64)))
    core = IntervalJoinCore(schema, ts_col=0, val_col=2,
                            window_us=10_000_000, n_buckets=1 << 8,
                            lane_width=16)
    return fused_source_join_epoch(gen.chunk_fn(), exprs, core, cap), core


def test_profiling_adds_zero_dispatches_to_fused_q5():
    cap, k = 128, 4
    with count_dispatches() as c:
        fused, core = _q5_fused(cap)
        st = fused(core.init_state(), jnp.int64(0),
                   jax.random.PRNGKey(0), k)
        c.reset()
        for i in range(3):
            st = fused(st, jnp.int64((i + 1) * k * cap),
                       jax.random.PRNGKey(i + 1), k)
        # still EXACTLY one dispatch per epoch with profiling on
        assert c.counts[Q5_EPOCH] == 3, dict(c.counts)
    assert GLOBAL_PROFILER.counts()[Q5_EPOCH] == 4
    rec = GLOBAL_PROFILER.snapshot()[Q5_EPOCH]
    assert rec["compiles"] == 1 and rec["total_s"] > 0


def test_profiling_adds_zero_dispatches_to_fused_q7():
    cap, k = 128, 4
    with count_dispatches() as c:
        fused, core = _q7_fused(cap)
        out = fused(core.init_state(), jnp.int64(0),
                    jax.random.PRNGKey(0), k)
        c.reset()
        out = fused(out[0], jnp.int64(k * cap), jax.random.PRNGKey(1), k)
        assert c.counts[Q7_EPOCH] == 1, dict(c.counts)
    assert GLOBAL_PROFILER.counts()[Q7_EPOCH] == 2


def test_fused_epoch_aot_analysis_chip_free():
    """The roofline inputs exist on the CPU stand-in: AOT-lowering the
    recorded q5 epoch yields nonzero flops / bytes / temp figures
    without a chip (the ctl profile roofline path)."""
    cap, k = 128, 4
    fused, core = _q5_fused(cap)
    fused(core.init_state(), jnp.int64(0), jax.random.PRNGKey(0), k)
    a = GLOBAL_PROFILER.analyze(Q5_EPOCH)[Q5_EPOCH]
    assert a["cost"]["flops"] > 0 and a["cost"]["bytes_accessed"] > 0
    assert a["memory"]["temp_bytes"] > 0
    assert GLOBAL_PROFILER.peak_temp_bytes() == a["memory"]["temp_bytes"]


def test_profiled_epoch_still_lowers_for_tpu():
    """The wrapper must not eat the AOT surface the pallas-compile CI
    proxy drives (``.trace().lower(lowering_platforms=("tpu",))``)."""
    fused, core = _q5_fused(128)
    text = fused.trace(core.init_state(), jnp.int64(0),
                       jax.random.PRNGKey(0), 4).lower(
        lowering_platforms=("tpu",)).as_text()
    assert "stablehlo" in text or "mhlo" in text


# ---------------------------------------------------------------------------
# HBM ledger
# ---------------------------------------------------------------------------


def test_hbm_ledger_headroom_and_flags():
    jobs = {
        "small": {"bytes": 100, "executors": {"HashAgg": 100},
                  "worker": None},
        "big": {"bytes": 900, "executors": {"HashJoin": 900}, "worker": 1},
    }
    led = hbm_ledger(jobs, capacity_bytes=2000, peak_temp_bytes=50,
                     warn_fraction=0.4)
    assert led["state_bytes"] == 1000
    assert led["used_bytes"] == 1050
    assert led["headroom_bytes"] == 950
    assert 0 < led["utilization"] < 1
    # big: 900 + 50 >= 0.4 * 2000 -> flagged; small: 150 < 800 -> not
    assert led["flagged"] == ["big"]
    assert led["jobs"]["big"]["worker"] == 1


def test_hbm_ledger_zero_capacity_never_divides():
    led = hbm_ledger({}, capacity_bytes=0)
    assert led["utilization"] == 0.0 and led["flagged"] == []


# ---------------------------------------------------------------------------
# roofline
# ---------------------------------------------------------------------------


def test_roofline_report_intensity_and_bounds():
    analyses = {
        "mem_bound": {"cost": {"flops": 1e6, "bytes_accessed": 1e6},
                      "memory": {"temp_bytes": 1}},
        "compute_bound": {"cost": {"flops": 1e9, "bytes_accessed": 1e3},
                          "memory": {}},
        "broken": {"error": "boom"},
    }
    rep = roofline_report(analyses, peak_flops=1e12, peak_bandwidth=1e10)
    assert rep["critical_intensity"] == 100.0
    mb = rep["kernels"]["mem_bound"]
    assert mb["intensity"] == 1.0 and mb["bound"] == "memory"
    assert mb["attainable_flops"] == 1e10
    assert mb["pct_of_peak_flops"] == 1.0
    cb = rep["kernels"]["compute_bound"]
    assert cb["bound"] == "compute" and cb["pct_of_peak_flops"] == 100.0
    assert "error" in rep["kernels"]["broken"]
    table = render_roofline_table(rep)
    assert "mem_bound" in table and "% of peak" in table


# ---------------------------------------------------------------------------
# bench trend
# ---------------------------------------------------------------------------


def _write_round(dirpath, n, parsed, rc=0):
    with open(os.path.join(dirpath, f"BENCH_r{n:02d}.json"), "w") as f:
        json.dump({"n": n, "rc": rc, "parsed": parsed}, f)


def test_bench_trend_flags_rate_drop_and_latency_rise(tmp_path):
    d = str(tmp_path)
    _write_round(d, 1, {"rows_per_sec": 100.0, "p99_ms": 5.0})
    _write_round(d, 2, {"rows_per_sec": 120.0, "p99_ms": 4.0})
    _write_round(d, 3, {"rows_per_sec": 60.0, "p99_ms": 9.0})
    trend = bench_trend(load_bench_history(d), tolerance=0.2)
    assert set(trend["regressions"]) == {"rows_per_sec", "p99_ms"}
    f = trend["fields"]["rows_per_sec"]
    assert not f["lower_is_better"] and f["best"] == 120.0 \
        and f["latest"] == 60.0
    assert trend["fields"]["p99_ms"]["lower_is_better"]
    table = render_trend_table(trend)
    assert "REGRESSED" in table


def test_bench_trend_within_tolerance_not_flagged(tmp_path):
    d = str(tmp_path)
    _write_round(d, 1, {"rows_per_sec": 100.0})
    _write_round(d, 2, {"rows_per_sec": 90.0})   # -10% < 20% tolerance
    trend = bench_trend(load_bench_history(d))
    assert trend["regressions"] == []


def test_bench_trend_partial_records_and_nested_fields(tmp_path):
    d = str(tmp_path)
    _write_round(d, 1, {"serving": {"qps": 50.0}})
    with open(os.path.join(d, "BENCH_partial.json"), "w") as f:
        f.write(json.dumps({"phase": "serving",
                            "record": {"serving": {"qps": 10.0}}}) + "\n")
        f.write("not json\n")                     # tolerated
    trend = bench_trend(load_bench_history(d))
    assert "serving.qps" in trend["regressions"]
    assert [p["value"] for p in
            trend["fields"]["serving.qps"]["points"]] == [50.0, 10.0]


def test_bench_trend_over_checked_in_rounds():
    """The acceptance artifact: the real BENCH_r01–r05 history folds
    into a trend (r03–r05 lost the chip round, so the headline 'value'
    field regresses vs r02's healthy 96k rows/s)."""
    history = load_bench_history(REPO)
    assert len(history) >= 5
    trend = bench_trend(history)
    assert "value" in trend["fields"]
    assert "value" in trend["regressions"]


@pytest.mark.slow
def test_ctl_bench_trend_cli():
    res = subprocess.run(
        [sys.executable, "-m", "risingwave_tpu", "ctl", "bench", "trend",
         "--bench-dir", REPO, "--json"],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, cwd=REPO)
    assert res.returncode == 0, res.stderr
    trend = json.loads(res.stdout)
    assert "value" in trend["regressions"]


@pytest.mark.slow
def test_ctl_profile_roofline_cli():
    """The acceptance artifact: `ctl profile roofline` emits per-kernel
    flops/bytes/intensity/%-of-peak for the q5 AND q7 fused epochs on
    the CPU stand-in, chip-free, via AOT lowering."""
    res = subprocess.run(
        [sys.executable, "-m", "risingwave_tpu", "ctl", "profile",
         "roofline", "--json", "--peak-flops", "1e14",
         "--peak-bandwidth", "1e12"],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, cwd=REPO)
    assert res.returncode == 0, res.stderr
    rep = json.loads(res.stdout)
    assert rep["peak_flops"] == 1e14
    for qn in (Q5_EPOCH, Q7_EPOCH):
        k = rep["kernels"][qn]
        assert k["flops"] > 0 and k["bytes_accessed"] > 0
        assert k["bound"] in ("memory", "compute")
        assert 0 <= k["pct_of_peak_flops"] <= 100
        assert k["memory"]["temp_bytes"] > 0


# ---------------------------------------------------------------------------
# Session surfaces
# ---------------------------------------------------------------------------


def test_session_metrics_profiling_and_dispatch_sections():
    from risingwave_tpu.frontend import Session
    from risingwave_tpu.frontend.prometheus import render_metrics

    cap, k = 128, 4
    fused, core = _q5_fused(cap)
    fused(core.init_state(), jnp.int64(0), jax.random.PRNGKey(0), k)
    s = Session()
    try:
        s.run_sql("CREATE TABLE t (a BIGINT, b BIGINT)")
        s.run_sql("CREATE MATERIALIZED VIEW m AS "
                  "SELECT a, count(*) AS c FROM t GROUP BY a")
        s.run_sql("INSERT INTO t VALUES (1, 10), (2, 20)")
        s.flush()
        m = s.metrics()
        prof = m["profiling"]
        assert prof["enabled"]
        rec = prof["dispatch"][Q5_EPOCH]
        assert rec["calls"] >= 1 and rec["total_s"] > 0 \
            and rec["compiles"] >= 1
        # HBM ledger over the live job's federated state bytes
        hbm = prof["hbm"]
        assert hbm["capacity_bytes"] == s.observability.hbm_capacity_bytes
        assert "m" in hbm["jobs"] and hbm["jobs"]["m"]["bytes"] > 0
        assert hbm["jobs"]["m"]["worker"] is None       # session-local
        assert hbm["headroom_bytes"] < hbm["capacity_bytes"]
        assert hbm["state_bytes"] >= hbm["jobs"]["m"]["bytes"]
        # the live dispatch-counter twin (satellite: reachable outside
        # bench --smoke / tests)
        assert m["dispatch"]["counts"][Q5_EPOCH] >= 1
        # Prometheus families
        text = render_metrics(s)
        assert "# TYPE rw_dispatch_total counter" in text
        assert "# TYPE rw_dispatch_seconds counter" in text
        assert "# TYPE rw_compile_total counter" in text
        assert 'rw_hbm_bytes{job="m",executor="_total"}' in text
        assert "rw_hbm_headroom_bytes " in text
    finally:
        s.close()


def test_session_dispatch_per_epoch_invariant_live():
    """metrics()["dispatch"]["per_epoch"] reads ~1.0 for a co-scheduled
    group's epoch qualname — the one-dispatch invariant, live."""
    from risingwave_tpu.frontend import Session
    from risingwave_tpu.frontend.build import BuildConfig

    s = Session(config=BuildConfig(coschedule=True,
                                   agg_table_capacity=1 << 12),
                source_chunk_capacity=128)
    try:
        s.run_sql(
            "CREATE SOURCE bid (auction BIGINT, bidder BIGINT, price "
            "BIGINT, channel VARCHAR, url VARCHAR, date_time TIMESTAMP, "
            "extra VARCHAR) WITH (connector = 'nexmark', "
            "nexmark_table = 'bid')")
        s.run_sql("CREATE MATERIALIZED VIEW m0 AS SELECT auction, "
                  "count(*) AS c FROM bid GROUP BY auction")
        GLOBAL_PROFILER.reset()          # drop the build-time compile call
        for _ in range(4):
            s.tick()
        d = s.metrics()["dispatch"]
        qn = "build_group_epoch.<locals>.coscheduled_epoch"
        assert d["counts"][qn] == 4
        assert d["per_epoch"][qn] == 1.0
        # the profiler's counts are cumulative across the process, so a
        # DROP + re-CREATE must retire the dead group's epochs or the
        # ratio would read 2.0 and falsely flag a dispatch regression
        s.run_sql("DROP MATERIALIZED VIEW m0")
        assert s._dispatch_epochs_retired[qn] == 4
        s.run_sql("CREATE MATERIALIZED VIEW m0 AS SELECT auction, "
                  "count(*) AS c FROM bid GROUP BY auction")
        for _ in range(4):
            s.tick()
        d = s.metrics()["dispatch"]
        assert d["counts"][qn] == 8
        assert d["per_epoch"][qn] == 1.0
    finally:
        s.close()


def test_session_dispatch_per_epoch_invariant_tick_compiled():
    """The tick compiler's twin of the invariant above (ISSUE 19
    satellite): the schedule DISSOLVES on every DDL, so a DROP +
    re-CREATE retires the dead padded group's epochs-run via
    TickCompiler.take_retired — otherwise the live per_epoch ratio
    would read 2.0 after the recompile and falsely flag a dispatch
    regression."""
    from risingwave_tpu.frontend import Session
    from risingwave_tpu.frontend.build import BuildConfig
    from risingwave_tpu.stream.tick_compiler import PADDED_EPOCH_FN

    s = Session(config=BuildConfig(tick_compiler=True,
                                   agg_table_capacity=1 << 12),
                source_chunk_capacity=128)
    try:
        s.run_sql(
            "CREATE SOURCE bid (auction BIGINT, bidder BIGINT, price "
            "BIGINT, channel VARCHAR, url VARCHAR, date_time TIMESTAMP, "
            "extra VARCHAR) WITH (connector = 'nexmark', "
            "nexmark_table = 'bid')")
        mv = ("CREATE MATERIALIZED VIEW {n} AS SELECT auction, "
              "sum(price + {lit}) AS v FROM bid GROUP BY auction")
        s.run_sql(mv.format(n="h0", lit=10))
        s.run_sql(mv.format(n="h1", lit=20))   # same skeleton => padded
        GLOBAL_PROFILER.reset()
        for _ in range(4):
            s.tick()
        d = s.metrics()["dispatch"]
        assert d["counts"][PADDED_EPOCH_FN] == 4
        assert d["per_epoch"][PADDED_EPOCH_FN] == 1.0
        # DROP dissolves the schedule: its 4 epochs-run must land in the
        # retirement ledger. Re-CREATE before the next tick so the
        # surviving singleton never runs a mega interlude.
        s.run_sql("DROP MATERIALIZED VIEW h1")
        assert s._dispatch_epochs_retired[PADDED_EPOCH_FN] == 4
        s.run_sql(mv.format(n="h1", lit=20))
        for _ in range(4):
            s.tick()
        d = s.metrics()["dispatch"]
        assert d["counts"][PADDED_EPOCH_FN] == 8
        assert d["per_epoch"][PADDED_EPOCH_FN] == 1.0
    finally:
        s.close()


@pytest.mark.slow
def test_hbm_ledger_federates_from_two_workers(tmp_path):
    """Acceptance: the ledger covers jobs hosted on >= 2 worker
    PROCESSES, attributed to their hosting worker, through the existing
    stats federation."""
    from risingwave_tpu.frontend import Session

    s = Session(workers=2, seed=11, data_dir=str(tmp_path / "c"))
    try:
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, v BIGINT)")
        # grouped aggs: the HashAgg state is device arrays, so the
        # ledger charges real bytes for both worker-hosted jobs
        s.run_sql("CREATE MATERIALIZED VIEW m1 AS SELECT v, count(*) "
                  "AS c FROM t GROUP BY v")
        s.run_sql("CREATE MATERIALIZED VIEW m2 AS SELECT v, sum(k) "
                  "AS sk FROM t GROUP BY v")
        assert {"m1", "m2"} <= set(s._remote_specs)
        s.run_sql("INSERT INTO t VALUES (1, 10), (2, 20)")
        s.flush()
        hbm = s.metrics()["profiling"]["hbm"]
        owners = {name: j["worker"] for name, j in hbm["jobs"].items()
                  if name in ("m1", "m2")}
        assert len(owners) == 2
        assert sorted(set(owners.values())) == [0, 1], owners
        assert all(hbm["jobs"][n]["bytes"] > 0 for n in owners)
        assert hbm["state_bytes"] >= sum(
            hbm["jobs"][n]["bytes"] for n in owners)
    finally:
        s.close()


def test_observability_config_round_trip(tmp_path):
    """[observability] knobs load from TOML, round-trip through
    rw_config, and feed the session (span ring capacity + slow-epoch
    threshold moved here; [streaming] stays a legacy alias)."""
    from risingwave_tpu.common.config import load_config
    from risingwave_tpu.frontend import Session

    p = tmp_path / "rw.toml"
    p.write_text("""
[observability]
profiling = false
trace_ring_capacity = 512
slow_epoch_threshold_ms = 25.5
hbm_capacity_bytes = 1073741824
chip_peak_flops = 1e14
""")
    cfg = load_config(str(p))
    assert cfg.observability.profiling is False
    assert cfg.observability.trace_ring_capacity == 512
    assert cfg.observability.slow_epoch_threshold_ms == 25.5
    assert cfg.observability.hbm_capacity_bytes == 1 << 30
    assert cfg.observability.chip_peak_flops == 1e14
    ring0 = GLOBAL_TRACE.capacity
    s = Session(rw_config=cfg)
    try:
        assert s.observability.profiling is False
        assert GLOBAL_PROFILER.enabled is False
        assert s.slow_epoch_threshold_ms == 25.5
        assert GLOBAL_TRACE.capacity == 512
        assert s.metrics()["profiling"]["hbm"]["capacity_bytes"] == 1 << 30
    finally:
        s.close()
        GLOBAL_TRACE.set_capacity(ring0)

    # legacy [streaming] aliases still work when [observability] is
    # untouched
    p2 = tmp_path / "legacy.toml"
    p2.write_text("[streaming]\nslow_epoch_threshold_ms = 7.0\n")
    s2 = Session(rw_config=load_config(str(p2)))
    try:
        assert s2.slow_epoch_threshold_ms == 7.0
    finally:
        s2.close()

    # an [observability] value set to the documented DEFAULT still wins
    # over a legacy alias (unset-inherits is None, not value==default):
    # here the operator explicitly disables the detector while an old
    # [streaming] stanza still arms it
    p3 = tmp_path / "both.toml"
    p3.write_text("""
[streaming]
slow_epoch_threshold_ms = 7.0
[observability]
slow_epoch_threshold_ms = 0.0
""")
    s3 = Session(rw_config=load_config(str(p3)))
    try:
        assert s3.slow_epoch_threshold_ms == 0.0
    finally:
        s3.close()
