"""Leader failover (ISSUE 18): TTL leases, term-fenced election,
standby promotion.

Fast tier: the lease protocol itself — the CAS race admits exactly one
candidate (typed LeaseLost for the loser, never a retryable conflict),
renewals under a superseded lease are refused, the client NEVER retries
lease.acquire/lease.renew over a broken link (a replayed acquire after a
competitor won would be a split brain), the server's TTL detector pushes
exactly ONE leader_down per term, and a slow meta link delays heartbeats
without ever expiring a live holder's lease.

Slow tier: the full promotion lifecycle over real Sessions (writer dies
→ standby promotes in place, pinned readers keep their SSTs, the fenced
ex-writer demotes to serving) and the kill -9 acceptance scenario
(sim.run_failover).
"""

import json
import os
import subprocess
import sys
import threading
import time

import pytest

from risingwave_tpu.meta.client import (
    LeaseLost, MetaClient, MetaUnavailable,
)
from risingwave_tpu.meta.server import MetaServer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _server(tmp_path, ttl: float = 30.0):
    srv = MetaServer(data_dir=str(tmp_path / "meta"), lease_ttl_s=ttl)
    return srv, srv.start()


def _poll(fn, timeout=15.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        out = fn()
        if out:
            return out
        time.sleep(interval)
    raise AssertionError(f"poll timed out after {timeout}s: {fn}")


class TestLeaseProtocol:
    def test_cas_race_admits_exactly_one(self, tmp_path):
        """Satellite (split-brain regression): two sessions race
        lease.acquire at the SAME term; the store CAS admits exactly
        one, and the loser gets the typed LeaseLost — not a retryable
        txn_conflict an eager client might replay into a split brain."""
        srv, addr = _server(tmp_path)
        a = MetaClient(addr, session_id="cand-a")
        b = MetaClient(addr, session_id="cand-b")
        try:
            results = {}
            gate = threading.Barrier(2)

            def race(name, client):
                gate.wait()
                try:
                    client.acquire_leader(1, reason="election")
                    results[name] = "won"
                except LeaseLost:
                    results[name] = "lost"
                except Exception as e:  # noqa: BLE001 - typed-loss audit
                    results[name] = f"WRONG:{type(e).__name__}"

            ts = [threading.Thread(target=race, args=(n, c))
                  for n, c in (("a", a), ("b", b))]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=10)
            assert sorted(results.values()) == ["lost", "won"], results
            # the loser's term stays unset: it remains a clean
            # serving/standby session, not a half-writer
            winner, loser = ((a, b) if results["a"] == "won"
                             else (b, a))
            assert winner.generation == 1 and loser.generation is None
            info = winner.lease_info()
            assert info["holder"] == winner.session_id
            # an "election"-reason acquire counts as a failover
            assert info["term"] == 1 and info["failovers"] == 1
        finally:
            a.close()
            b.close()
            srv.stop()

    def test_renew_under_superseded_lease_is_lease_lost(self, tmp_path):
        srv, addr = _server(tmp_path)
        old = MetaClient(addr, session_id="old-writer")
        new = MetaClient(addr, session_id="new-writer")
        try:
            old.acquire_leader(1)
            # a strictly newer term is admitted even over a LIVE holder
            # (the takeover attach path); the old writer's next renewal
            # must come back as the typed loss, stopping its heartbeat
            new.acquire_leader(2)
            with pytest.raises(LeaseLost):
                old.renew_leader()
            # re-asserting the stale term is refused the same way
            with pytest.raises(LeaseLost):
                old.acquire_leader(1)
        finally:
            old.close()
            new.close()
            srv.stop()

    def test_stale_or_equal_term_refused_both_live_and_expired(
            self, tmp_path):
        srv, addr = _server(tmp_path, ttl=0.3)
        w = MetaClient(addr, session_id="w")
        c = MetaClient(addr, session_id="challenger")
        try:
            w.acquire_leader(1)
            with pytest.raises(LeaseLost, match="live"):
                c.acquire_leader(1)
            _poll(lambda: w.lease_info().get("expired"))
            # expiry alone never transfers the lease at the SAME term:
            # candidates must go through leader_down's term + 1
            with pytest.raises(LeaseLost, match="expired"):
                c.acquire_leader(1)
            assert c.acquire_leader(2, reason="election") == 2
        finally:
            w.close()
            c.close()
            srv.stop()

    def test_lease_methods_never_retried(self, tmp_path, monkeypatch):
        """Satellite (retry audit): a broken connection during
        store.put is reconnected and replayed (idempotent), but
        lease.acquire/lease.renew are NEVER retried — the reply may
        have been lost AFTER a competitor won, and a replay would
        acquire a lease the client must not hold."""
        srv, addr = _server(tmp_path)
        c = MetaClient(addr, session_id="audit")
        attempts = []
        orig = MetaClient._request

        def flaky(self, method, params=None):
            attempts.append(method)
            if method in ("store.put", "lease.acquire", "lease.renew") \
                    and attempts.count(method) == 1:
                self._drop_conn()
                raise ConnectionError("injected link break")
            return orig(self, method, params)

        monkeypatch.setattr(MetaClient, "_request", flaky)
        try:
            c.call("store.put", {"key": "k", "value": "v"})
            assert attempts.count("store.put") == 2      # retried
            assert c.call("store.get", {"key": "k"}) == "v"
            with pytest.raises(MetaUnavailable, match="not retried"):
                c.acquire_leader(1)
            assert attempts.count("lease.acquire") == 1  # NOT retried
            assert c.generation is None
            # the server never saw the acquire: a clean client takes it
            c.acquire_leader(1)
            c.generation = 1
            with pytest.raises(MetaUnavailable, match="not retried"):
                c.renew_leader()
            assert attempts.count("lease.renew") == 1    # NOT retried
        finally:
            c.close()
            srv.stop()

    def test_expiry_pushes_exactly_one_leader_down(self, tmp_path):
        srv, addr = _server(tmp_path, ttl=0.3)
        w = MetaClient(addr, session_id="w")
        obs = MetaClient(addr, session_id="obs")
        downs = []
        obs.notifications.subscribe(
            "leader_down", lambda _v, info: downs.append(info))
        try:
            w.acquire_leader(1)          # no heartbeat: left to expire
            _poll(lambda: downs)
            time.sleep(0.8)              # detector keeps polling...
            assert len(downs) == 1, downs    # ...but pushes ONCE per term
            assert downs[0]["term"] == 1
            s = MetaClient(addr, session_id="standby")
            try:
                assert s.acquire_leader(
                    downs[0]["term"] + 1, reason="election") == 2
                info = s.lease_info()
                assert info["failovers"] == 1
                assert info["reason"] == "election"
                assert [h["term"] for h in info["history"]] == [1, 2]
                assert info["history"][-1]["leaderless_s"] >= 0
            finally:
                s.close()
        finally:
            w.close()
            obs.close()
            srv.stop()

    def test_heartbeat_keeps_lease_alive_and_stops_on_loss(
            self, tmp_path):
        srv, addr = _server(tmp_path, ttl=0.4)
        w = MetaClient(addr, session_id="w")
        usurper = MetaClient(addr, session_id="usurper")
        lost = []
        try:
            w.acquire_leader(1)
            w.start_heartbeat(0.1, on_lost=lost.append)
            time.sleep(1.2)              # several TTLs: renewals hold it
            info = w.lease_info()
            assert info["term"] == 1 and not info["expired"]
            assert w.stats["heartbeats"] >= 3
            usurper.acquire_leader(2)
            _poll(lambda: lost)          # one typed loss, loop stopped
            assert isinstance(lost[0], LeaseLost)
            assert w.stats["lease_lost"] == 1
            hb = w.stats["heartbeats"]
            time.sleep(0.4)
            assert w.stats["heartbeats"] == hb   # loop really stopped
        finally:
            w.close()
            usurper.close()
            srv.stop()

    def test_slow_meta_link_never_expires_a_live_lease(self, tmp_path):
        """Satellite: seeded delay on every lease.renew frame (the
        meta#clease chaos stream) slows heartbeats down but must NEVER
        cause a spurious failover — the TTL outlives any delay the
        chaos plane injects."""
        from risingwave_tpu.meta.client import META_LINK
        from risingwave_tpu.rpc.faults import (
            ChaosRule, ChaosSchedule, install,
        )
        srv, addr = _server(tmp_path, ttl=0.6)
        install(ChaosSchedule(3, [
            ChaosRule(kind="delay", link=META_LINK,
                      types=["lease.renew"], prob=1.0, delay_ms=50.0),
        ], name="slow_renew"))
        w = MetaClient(addr, session_id="w")
        try:
            w.acquire_leader(1)
            w.start_heartbeat(0.1)
            time.sleep(1.5)
            info = w.lease_info()
            assert info["term"] == 1 and not info["expired"], info
            assert info["failovers"] == 0
            assert w.stats["heartbeats"] >= 3
        finally:
            install(None)
            w.close()
            srv.stop()


@pytest.mark.slow
class TestCtlMetaLeader:
    def test_ctl_meta_leader_live_and_offline(self, tmp_path):
        """Satellite: `ctl meta leader` answers from a live server
        (holder/term/TTL remaining) and offline from the store dir
        (TTL unknown — the deadline is server memory). Slow tier: two
        subprocess interpreter spins; check.sh also smokes it."""
        srv, addr = _server(tmp_path)
        w = MetaClient(addr, session_id="ctl-test-writer")
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PYTHONPATH=REPO + os.pathsep
                   + os.environ.get("PYTHONPATH", ""))
        try:
            w.acquire_leader(1)
            live = subprocess.run(
                [sys.executable, "-m", "risingwave_tpu", "ctl", "meta",
                 "leader", "--meta-addr", addr, "--json"],
                capture_output=True, text=True, env=env, timeout=120)
            assert live.returncode == 0, live.stderr
            info = json.loads(live.stdout)
            assert info["holder"] == "ctl-test-writer"
            assert info["term"] == 1
            assert info["ttl_remaining_s"] is not None
        finally:
            w.close()
            srv.stop()
        off = subprocess.run(
            [sys.executable, "-m", "risingwave_tpu", "ctl", "meta",
             "leader", "--data-dir", str(tmp_path)],
            capture_output=True, text=True, env=env, timeout=120)
        assert off.returncode == 0, off.stderr
        assert "ctl-test-writer" in off.stdout
        assert "unknown (offline)" in off.stdout


DDL = "CREATE TABLE t1 (k BIGINT PRIMARY KEY, v BIGINT)"
MV = ("CREATE MATERIALIZED VIEW m1 AS SELECT v, count(*) AS n "
      "FROM t1 GROUP BY v")


@pytest.mark.slow
class TestPromotionLifecycle:
    def test_standby_promotes_reader_keeps_pins_ex_writer_demotes(
            self, tmp_path):
        """The tentpole end to end over real Sessions: the writer stops
        renewing (a partitioned heartbeat), the server declares it down,
        the standby auto-promotes in place and resumes conduction under
        term 2; a serving reader holding pinned SSTs keeps reading
        correct rows across the handover (the post-promotion vacuum
        grace window); the fenced ex-writer demotes to serving on its
        first refused publish instead of crashing."""
        from risingwave_tpu.frontend.session import Session

        d = str(tmp_path / "cluster")
        srv = MetaServer(data_dir=os.path.join(d, "meta"),
                         lease_ttl_s=1.0)
        addr = srv.start()
        w = Session(data_dir=d, meta_addr=addr, state_store="hummock",
                    checkpoint_frequency=2)
        standby = reader = None
        try:
            w.run_sql(DDL)
            w.run_sql(MV)
            for i in range(4):
                w.run_sql(f"INSERT INTO t1 VALUES ({i}, {i % 2})")
                w.tick()
            w.flush()
            standby = Session(data_dir=d, meta_addr=addr,
                              role="standby", checkpoint_frequency=2)
            reader = Session(data_dir=d, meta_addr=addr, role="serving")
            before = sorted(reader.run_sql("SELECT v, n FROM m1"))
            assert before == [(0, 2), (1, 2)]
            assert standby.role == "serving" and standby._standby

            # the writer's heartbeat dies (partition/SIGSTOP stand-in);
            # the TTL detector fires and the standby wins the election
            w.meta.stop_heartbeat()
            _poll(lambda: standby._leadership["promotions"] == 1,
                  timeout=30)
            assert standby.role == "writer"
            assert standby._generation == 2

            # pin safety: the reader keeps its pinned snapshot across
            # the handover — correct rows, no missing-SST error, even
            # after the promoted writer commits + compacts + vacuums
            for j in range(4):
                standby.run_sql(f"INSERT INTO t1 VALUES ({10 + j}, 7)")
                standby.tick()
            standby.flush()
            assert sorted(reader.run_sql("SELECT v, n FROM m1")) \
                == [(0, 2), (1, 2), (7, 4)]

            # the fenced ex-writer: first conduction attempt under the
            # lost lease raises MetaFenced, then it DEMOTES to serving
            # (no crash, no second conductor), still answering reads
            w.run_sql("INSERT INTO t1 VALUES (99, 99)")
            with pytest.raises(Exception, match="superseded|fenced"):
                for _ in range(3):
                    w.tick()
            assert _poll(lambda: w.role == "serving", timeout=15)
            assert w._fenced is False
            got = sorted(w.run_sql("SELECT v, n FROM m1"))
            assert got == [(0, 2), (1, 2), (7, 4)]
            # the discarded in-flight insert (99) left no trace — the
            # exactly-once "fully discarded" half
            assert (99, 99) not in standby.run_sql("SELECT k, v FROM t1")
            m = standby.metrics()["leadership"]
            assert m["role"] == "writer" and m["term"] == 2
            assert m["is_writer"] == 1 and m["promotions"] == 1
            assert w.metrics()["leadership"]["demotions"] == 1
        finally:
            for s in (reader, standby):
                if s is not None:
                    s.close()
            w.close()
            srv.stop()

    def test_rw_leader_history_catalog_relation(self, tmp_path):
        from risingwave_tpu.frontend.session import Session

        d = str(tmp_path / "cluster")
        srv = MetaServer(data_dir=os.path.join(d, "meta"),
                         lease_ttl_s=30.0)
        addr = srv.start()
        w = Session(data_dir=d, meta_addr=addr, state_store="hummock",
                    checkpoint_frequency=2)
        try:
            w.run_sql(DDL)
            rows = w.run_sql(
                "SELECT term, holder, reason, current "
                "FROM rw_catalog.rw_leader_history")
            assert len(rows) == 1
            term, holder, reason, current = rows[0]
            assert term == 1 and holder == w.meta.session_id
            assert reason == "bootstrap" and current
        finally:
            w.close()
            srv.stop()


@pytest.mark.slow
class TestKillDashNineFailover:
    def test_run_failover_kill9_acceptance(self):
        """The acceptance scenario (docs/control-plane.md): SIGKILL the
        writer PROCESS mid-stream under seeded chaos → a standby
        auto-promotes with no operator action, the split-brain probe
        stays green, and the committed rows replayed into a fresh
        control rebuild the identical MV (exactly-once)."""
        from risingwave_tpu.sim import run_failover

        r = run_failover(seed=11)
        assert r["failovers"] == 1
        assert r["terms"] == [1, 2]
        assert all(r["audit"].values()), r["audit"]
        assert r["elections_lost"] == 1
        assert r["mttr_ms"] < (r["lease_ttl_s"] + 30) * 1000
        assert r["trace"], "no deterministic injections recorded"
