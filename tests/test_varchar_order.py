"""VARCHAR ordering semantics (VERDICT r3 weak #2): every ordering
operation on strings must follow lexicographic order, never dictionary
insertion order. The device compares dictionary *ranks* via the
StringDict rank side table; state stores stable ids and ranks are looked
up fresh at comparison time (reference order semantics:
src/common/src/util/memcmp_encoding.rs).

The first three tests are the judge's round-3 repro cases verbatim.
"""

import pytest

from risingwave_tpu.common.types import GLOBAL_STRING_DICT
from risingwave_tpu.frontend import Session


def _table(rows=("zebra", "apple", "mango")):
    # intern order is deliberately non-alphabetical: 'zebra' gets the
    # smallest id, so raw-id comparisons are maximally wrong
    s = Session()
    s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, name VARCHAR)")
    vals = ", ".join(f"({i}, '{n}')" for i, n in enumerate(rows))
    s.run_sql(f"INSERT INTO t VALUES {vals}")
    s.flush()
    return s


class TestJudgeRepros:
    def test_order_by_limit(self):
        s = _table()
        out = s.run_sql("SELECT name FROM t ORDER BY name LIMIT 2")
        assert [r[0] for r in out] == ["apple", "mango"]

    def test_where_greater(self):
        s = _table()
        out = s.run_sql("SELECT name FROM t WHERE name > 'b'")
        assert sorted(r[0] for r in out) == ["mango", "zebra"]

    def test_min_agg(self):
        s = _table()
        out = s.run_sql("SELECT min(name) FROM t")
        assert out == [("apple",)]


class TestOrderingSurface:
    def test_order_by_desc(self):
        s = _table()
        out = s.run_sql("SELECT name FROM t ORDER BY name DESC LIMIT 3")
        assert [r[0] for r in out] == ["zebra", "mango", "apple"]

    def test_max_agg_and_grouped(self):
        s = _table()
        assert s.run_sql("SELECT max(name) FROM t") == [("zebra",)]
        s.run_sql("CREATE TABLE g (k BIGINT PRIMARY KEY, grp BIGINT, "
                  "name VARCHAR)")
        s.run_sql("INSERT INTO g VALUES (1, 0, 'pear'), (2, 0, 'fig'), "
                  "(3, 1, 'kiwi'), (4, 1, 'date')")
        s.flush()
        out = sorted(s.run_sql(
            "SELECT grp, min(name), max(name) FROM g GROUP BY grp"))
        assert out == [(0, "fig", "pear"), (1, "date", "kiwi")]

    def test_min_in_streaming_mv(self):
        """Grouped string MIN maintained incrementally across barriers,
        with strings interned AFTER the MV exists (rank table refresh)."""
        s = Session()
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, grp BIGINT, "
                  "name VARCHAR)")
        s.run_sql("CREATE MATERIALIZED VIEW m AS "
                  "SELECT grp, min(name) AS lo, max(name) AS hi "
                  "FROM t GROUP BY grp")
        s.run_sql("INSERT INTO t VALUES (1, 0, 'walnut'), (2, 0, 'pecan')")
        s.flush()
        assert s.mv_rows("m") == [(0, "pecan", "walnut")]
        # 'almond' interns later (highest id) but ranks lowest
        s.run_sql("INSERT INTO t VALUES (3, 0, 'almond')")
        s.flush()
        assert s.mv_rows("m") == [(0, "almond", "walnut")]

    def test_between_and_comparisons(self):
        s = _table(rows=("delta", "alpha", "echo", "bravo", "charlie"))
        out = s.run_sql(
            "SELECT name FROM t WHERE name >= 'bravo' AND name < 'delta'")
        assert sorted(r[0] for r in out) == ["bravo", "charlie"]

    def test_order_by_varchar_with_late_interned_strings(self):
        """TopN's incremental candidate path must refill when the dict
        grows: a string interned after the first flush can outrank the
        stored threshold."""
        s = Session()
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, name VARCHAR)")
        s.run_sql("CREATE MATERIALIZED VIEW top2 AS "
                  "SELECT k, name FROM t ORDER BY name LIMIT 2")
        s.run_sql("INSERT INTO t VALUES (1, 'yak'), (2, 'xenon')")
        s.flush()
        assert sorted(r[1] for r in s.mv_rows("top2")) == ["xenon", "yak"]
        s.run_sql("INSERT INTO t VALUES (3, 'aardvark')")
        s.flush()
        assert sorted(r[1] for r in s.mv_rows("top2")) == [
            "aardvark", "xenon"]

    def test_rank_table_is_dense_and_fresh(self):
        d = GLOBAL_STRING_DICT
        a = d.intern("zzz_rank_test")
        b = d.intern("aaa_rank_test")
        r = d.ranks()
        assert r[b] < r[a]
        # device table padded to pow2, padding above live ranks
        t = d.device_ranks()
        assert t.shape[0] >= d.version
        assert int(t[a]) == int(r[a]) and int(t[b]) == int(r[b])
