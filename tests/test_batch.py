"""Batch engine (coverage #70): one-shot executors over snapshots +
vnode-partitioned parallel tasks."""

import pytest

from risingwave_tpu.batch import (
    BatchFilter, BatchHashAgg, BatchLimit, BatchProject, BatchSort,
    BatchTaskManager, RowSeqScan, run_batch,
)
from risingwave_tpu.batch.task import vnode_partitions
from risingwave_tpu.common.hashing import VNODE_COUNT
from risingwave_tpu.common.types import INT64, Field, Schema
from risingwave_tpu.expr.agg import agg, count_star
from risingwave_tpu.expr.expr import InputRef, Literal, call
from risingwave_tpu.ops.topn import OrderSpec
from risingwave_tpu.storage.state_store import MemoryStateStore
from risingwave_tpu.storage.state_table import StateTable

SCHEMA = Schema((Field("k", INT64), Field("g", INT64), Field("v", INT64)))


def _table(n=100):
    store = MemoryStateStore()
    t = StateTable(store, 1, SCHEMA, [0])
    for i in range(n):
        t.insert((i, i % 3, i * 10))
    t.commit(1)
    store.commit(1)
    return t


class TestExecutors:
    def test_scan_filter_project(self):
        t = _table(10)
        scan = RowSeqScan(t, batch_size=4)
        filt = BatchFilter(scan, call("greater_than",
                                      InputRef(2, INT64), Literal(50, INT64)))
        proj = BatchProject(filt, [InputRef(0, INT64)], names=("k",))
        rows = run_batch(proj)
        assert sorted(r[0] for r in rows) == [6, 7, 8, 9]

    def test_hash_agg_sort_limit(self):
        t = _table(9)    # k: 0..8, g = k%3, v = k*10
        plan = BatchLimit(
            BatchSort(
                BatchHashAgg(RowSeqScan(t), [1],
                             [count_star(), agg("sum", 2, INT64)]),
                [OrderSpec(2, desc=True)]),   # by sum desc
            limit=2)
        rows = run_batch(plan)
        # g=2: 20+50+80=150; g=1: 10+40+70=120; g=0: 0+30+60=90
        assert rows == [(2, 3, 150), (1, 3, 120)]

    def test_vnode_partitioned_scan_covers_all_rows(self):
        t = _table(60)
        parts = vnode_partitions(4)
        assert sum(len(p) for p in parts) == VNODE_COUNT
        rows = []
        for part in parts:
            rows.extend(run_batch(RowSeqScan(t, vnodes=part)))
        assert sorted(r[0] for r in rows) == list(range(60))


class TestTaskManager:
    def test_fire_partitioned(self):
        t = _table(40)
        tm = BatchTaskManager(max_workers=4)
        try:
            ids = tm.fire_partitioned(
                lambda vnodes: RowSeqScan(t, vnodes=vnodes), n_tasks=4)
            rows = tm.collect_all(ids)
            assert sorted(r[0] for r in rows) == list(range(40))
        finally:
            tm.shutdown()
