"""Watermark-driven state cleaning (VERDICT r2 item 4): bounded state across
many windows for hash agg and interval hash join, with checkpoint/compaction
correctness (no broken probe chains after rebuild)."""

import asyncio

import jax.numpy as jnp
import numpy as np
import pytest

from risingwave_tpu.common import INT64, TIMESTAMP, Schema, chunk_to_rows, make_chunk
from risingwave_tpu.expr.agg import agg as agg_call, count_star
from risingwave_tpu.ops.join_state import JoinType
from risingwave_tpu.storage.state_store import MemoryStateStore
from risingwave_tpu.storage.state_table import StateTable
from risingwave_tpu.stream.executor import collect_until_barrier
from risingwave_tpu.stream.hash_agg import HashAggExecutor, agg_state_schema
from risingwave_tpu.stream.hash_join import HashJoinExecutor
from risingwave_tpu.stream.message import Barrier, Watermark
from risingwave_tpu.stream.source import MockSource

S_WIN = Schema.of(("w", TIMESTAMP), ("k", INT64), ("v", INT64))
S_TIME = Schema.of(("k", INT64), ("t", TIMESTAMP))


def run(coro):
    return asyncio.run(coro)


async def drain_collect(ex):
    chunks = []
    async for m in ex.execute():
        from risingwave_tpu.common.chunk import StreamChunk
        if isinstance(m, StreamChunk):
            chunks.append(m)
    return chunks


def live_agg_groups(ex: HashAggExecutor) -> int:
    st = ex.state
    return int(jnp.sum(st.table.occupied & (st.lanes[0] > 0)))


def occupied_ht_slots(ex: HashAggExecutor) -> int:
    return int(jnp.sum(ex.state.table.occupied))


def test_agg_state_bounded_across_windows():
    """Stream 40 windows with a trailing watermark; live groups AND occupied
    hash-table slots stay bounded near one window's worth, while the emitted
    results cover every window."""
    msgs = [Barrier.new(1)]
    epoch = 1
    for w in range(40):
        rows = [(w * 1000, k, 1) for k in range(8)]
        msgs.append(make_chunk(S_WIN, rows, capacity=16))
        msgs.append(Watermark(0, w * 1000))  # window w closed
        epoch += 1
        msgs.append(Barrier.new(epoch, checkpoint=True))
    src = MockSource(S_WIN, msgs)
    ex = HashAggExecutor(src, [0, 1], [count_star()], table_capacity=256,
                         out_capacity=64)
    chunks = run(drain_collect(ex))
    emitted = [r for c in chunks for r in chunk_to_rows(c, ex.schema)]
    # every (window, k) group was emitted exactly once as an insert
    assert len({(r[0], r[1]) for r in emitted}) == 40 * 8
    # state bounded: only the last window's groups survive; table slots
    # reclaimed by compaction (not 40*8 = 320 > capacity would have overflowed)
    assert live_agg_groups(ex) <= 8
    assert occupied_ht_slots(ex) <= 8


def test_agg_cleaning_persists_deletes():
    """Cleaned groups are deleted from the durable state table; recovery
    reloads only live groups."""
    store = MemoryStateStore()
    schema = agg_state_schema([S_WIN[0], S_WIN[1]], [count_star()])
    table = StateTable(store, 3, schema, [0, 1])
    msgs = [Barrier.new(1),
            make_chunk(S_WIN, [(0, 1, 1), (0, 2, 1)], capacity=8),
            Barrier.new(2, checkpoint=True),
            make_chunk(S_WIN, [(1000, 1, 1)], capacity=8),
            Watermark(0, 1000),
            Barrier.new(3, checkpoint=True)]
    src = MockSource(S_WIN, msgs)
    ex = HashAggExecutor(src, [0, 1], [count_star()], state_table=table,
                         table_capacity=64, out_capacity=16)
    run(drain_collect(ex))
    store.commit(3)
    rows = list(StateTable(store, 3, schema, [0, 1]).scan_all())
    assert [(r[0], r[1]) for r in rows] == [(1000, 1)]

    # recovery sees only the live group
    src2 = MockSource(S_WIN, [Barrier.new(4)])
    ex2 = HashAggExecutor(src2, [0, 1], [count_star()],
                          state_table=StateTable(store, 3, schema, [0, 1]),
                          table_capacity=64, out_capacity=16)
    assert live_agg_groups(ex2) == 1


def test_compact_preserves_lookups():
    """After clean+compact, updates to surviving groups still find them
    (rebuilt probe chains), and re-inserting a cleaned key starts fresh."""
    msgs = [Barrier.new(1)]
    # 50 groups, clean those below 40, then update survivors + revive a dead one
    msgs.append(make_chunk(S_WIN, [(g, g % 4, 1) for g in range(50)], capacity=64))
    msgs.append(Watermark(0, 40))
    msgs.append(Barrier.new(2, checkpoint=True))
    msgs.append(make_chunk(S_WIN, [(45, 1, 1), (10, 2, 1)], capacity=64))
    msgs.append(Barrier.new(3))
    src = MockSource(S_WIN, msgs)
    ex = HashAggExecutor(src, [0, 1], [count_star()], table_capacity=128,
                         out_capacity=64)
    run(drain_collect(ex))
    st = ex.state
    occ = np.asarray(st.table.occupied)
    keys = np.asarray(st.table.key_data[0])
    counts = np.asarray(st.lanes[0])
    live = {(int(keys[i])): int(counts[i]) for i in np.nonzero(occ)[0]
            if counts[i] > 0}
    assert live[45] == 2      # update found the surviving group
    assert live[10] == 1      # revived group starts fresh (old count cleaned)
    assert all(k >= 40 or k == 10 for k in live)


def host_interval_join(l_rows, r_rows, width):
    return sorted(
        (lr + rr) for lr in l_rows for rr in r_rows
        if lr[0] == rr[0] and abs(lr[1] - rr[1]) <= width)


def test_interval_join_bounded_state():
    """q7-shaped interval join: both sides cleaned by the opposite side's
    time watermark; state stays bounded across many windows and outputs
    match the host model."""
    WIDTH = 100
    n_windows = 30
    left_msgs = [Barrier.new(1)]
    right_msgs = [Barrier.new(1)]
    l_rows_all, r_rows_all = [], []
    epoch = 1
    for w in range(n_windows):
        t = w * 1000
        l_rows = [(k, t + k) for k in range(4)]
        r_rows = [(k, t + k + 10) for k in range(2)]
        l_rows_all += l_rows
        r_rows_all += r_rows
        left_msgs.append(make_chunk(S_TIME, l_rows, capacity=8))
        right_msgs.append(make_chunk(S_TIME, r_rows, capacity=8))
        left_msgs.append(Watermark(1, t))
        right_msgs.append(Watermark(1, t))
        epoch += 1
        left_msgs.append(Barrier.new(epoch, checkpoint=True))
        right_msgs.append(Barrier.new(epoch, checkpoint=True))
    left = MockSource(S_TIME, left_msgs)
    right = MockSource(S_TIME, right_msgs)
    # real interval condition: |l.t - r.t| <= WIDTH over the combined schema
    from risingwave_tpu.expr import Literal, call, col
    lt_ = col(1, TIMESTAMP)
    rt_ = col(3, TIMESTAMP)
    w_ = Literal(WIDTH, INT64)
    cond = call("and",
                call("less_than_or_equal", call("subtract", lt_, rt_), w_),
                call("less_than_or_equal", call("subtract", rt_, lt_), w_))
    ex = HashJoinExecutor(
        left, right, [0], [0], JoinType.INNER, condition=cond,
        key_capacity=64, bucket_width=8, out_capacity=64,
        interval_clean=(
            # clean each side's rows once the OPPOSITE side's watermark
            # passes them by the interval width
            ("left", 1, "right", 1, WIDTH),
            ("right", 1, "left", 1, WIDTH),
        ))
    chunks = run(drain_collect(ex))
    got = sorted(r for c in chunks for r in chunk_to_rows(c, ex.schema))
    exp = host_interval_join(l_rows_all, r_rows_all, WIDTH)
    assert got == exp
    assert len(got) == n_windows * 2  # k in {0,1} matches each window
    # state bounded: far fewer lanes live than total rows ingested
    live_l = int(jnp.sum(ex.state.left.occupied))
    live_r = int(jnp.sum(ex.state.right.occupied))
    assert live_l <= 8, live_l    # one window's worth, not 120
    assert live_r <= 4, live_r
    # ht slots reclaimed by compaction too
    assert int(jnp.sum(ex.state.left.ht.occupied)) <= 8


def test_interval_join_cleaning_persists_deletes():
    store = MemoryStateStore()
    lt = StateTable(store, 1, S_TIME, [0, 1])
    rt = StateTable(store, 2, S_TIME, [0, 1])
    left_msgs = [Barrier.new(1), make_chunk(S_TIME, [(1, 10), (2, 20)], capacity=8),
                 Barrier.new(2, checkpoint=True),
                 Watermark(1, 1000),
                 Barrier.new(3, checkpoint=True)]
    right_msgs = [Barrier.new(1), Barrier.new(2, checkpoint=True),
                  Barrier.new(3, checkpoint=True)]
    ex = HashJoinExecutor(
        MockSource(S_TIME, left_msgs), MockSource(S_TIME, right_msgs),
        [0], [0], JoinType.INNER, left_state_table=lt, right_state_table=rt,
        key_capacity=64, bucket_width=4,
        interval_clean=(("left", 1, "left", 1, 0),))
    run(drain_collect(ex))
    store.commit(3)
    assert list(StateTable(store, 1, S_TIME, [0, 1]).scan_all()) == []
