"""Elastic scaling plane: live vnode migration + backlog-driven
autoscaler (ISSUE 10, docs/scaling.md).

What these tests pin:
  * placement-diff math (meta/rescale.py): new actor ranges always equal
    the ``vnode_to_shard`` routing function, a 2→4/4→2 rescale moves
    EXACTLY half the ring (the minimal move set), worker balance stays
    within one vnode, and a same-parallelism plan is a no-op;
  * autoscaler policy (meta/autoscaler.py): hysteresis (no decision
    before N consecutive highs), cooldown (no second decision inside the
    window), no flapping under oscillating load, lazy scale-in, clamps;
  * LIVE migration (frontend/session.py rescale): a spanning grouped-agg
    job rescales 2→4 mid-stream with only the changed vnode ranges
    handed off as state refs, bit-exact vs a no-rescale control, worker
    processes untouched (same pids), migration metrics populated, and
    the persisted placement redeploying on restart;
  * kill -9 mid-migration rolls BACK to the old placement via generation
    fencing (pre-commit) or FORWARD under the new one (post-commit),
    converging bit-exact either way;
  * whole-job remote placements refuse rescale loudly (VERDICT #78) and
    session-local jobs delegate to the documented quiesce+rebuild path;
  * the seeded sim traffic-spike scenario: the autoscaler triggers the
    same 2→4 rescale autonomously from injected backlog and does not
    flap when the load subsides (slow tier).
"""

import pytest

from risingwave_tpu.common.config import AutoscalerConfig
from risingwave_tpu.common.hashing import VNODE_COUNT, vnode_to_shard
from risingwave_tpu.frontend import Session
from risingwave_tpu.frontend.build import BuildConfig
from risingwave_tpu.meta.autoscaler import Autoscaler
from risingwave_tpu.meta.fragment import FragmentScheduler, span_plan
from risingwave_tpu.meta.rescale import (
    RescaleUnsupported, actor_ranges, diff_placements, plan_rescale,
)

CAP = 64

BID_DDL = """CREATE SOURCE bid (auction BIGINT, bidder BIGINT, price BIGINT,
channel VARCHAR, url VARCHAR, date_time TIMESTAMP, extra VARCHAR)
WITH (connector = 'nexmark', nexmark_table = 'bid')"""

AGG = ("CREATE MATERIALIZED VIEW q AS SELECT auction, count(*) AS n, "
       "max(price) AS mx FROM bid GROUP BY auction")

Q5 = """CREATE MATERIALIZED VIEW q5 AS
    SELECT AuctionBids.auction, AuctionBids.num FROM (
        SELECT bid.auction, count(*) AS num, window_start AS starttime
        FROM HOP(bid, date_time, INTERVAL '2' SECOND, INTERVAL '10' SECOND)
        GROUP BY window_start, bid.auction
    ) AS AuctionBids
    JOIN (
        SELECT max(CountBids.num) AS maxn, CountBids.starttime_c
        FROM (
            SELECT count(*) AS num, window_start AS starttime_c
            FROM HOP(bid, date_time, INTERVAL '2' SECOND,
                     INTERVAL '10' SECOND)
            GROUP BY bid.auction, window_start
        ) AS CountBids
        GROUP BY CountBids.starttime_c
    ) AS MaxBids
    ON AuctionBids.starttime = MaxBids.starttime_c
       AND AuctionBids.num = MaxBids.maxn"""


def _agg_graph():
    """A span graph with one shardable fragment, built through the real
    frontend pipeline (a session without workers is cheap)."""
    from risingwave_tpu.frontend.parser import parse_one
    s = Session(seed=42)
    try:
        s.run_sql(BID_DDL)
        stmt = parse_one(AGG)
        return span_plan(s._plan(stmt.query))
    finally:
        s.close()


def _par(placement) -> int:
    return max(len(a) for a in placement.actors.values())


class TestPlacementPlan:
    def test_ranges_equal_routing_function(self):
        """Per-actor ranges ARE the vnode_to_shard mapping, for every
        parallelism — placement and routing cannot diverge."""
        for n in (1, 2, 3, 4, 5, 7, 8):
            ranges = actor_ranges(VNODE_COUNT, n)
            assert ranges[0][0] == 0 and ranges[-1][1] == VNODE_COUNT
            for a, (s, e) in enumerate(ranges):
                for v in (s, e - 1):
                    assert int(vnode_to_shard(v, n)) == a

    def test_balance_within_one_for_divisors(self):
        for n in (1, 2, 4, 8, 16):
            sizes = [e - s for s, e in actor_ranges(VNODE_COUNT, n)]
            assert max(sizes) - min(sizes) <= 1

    def test_2_to_4_moves_exactly_half_the_ring(self):
        g = _agg_graph()
        old = FragmentScheduler().place("q", g, [0, 1, 2, 3],
                                        parallelism=2)
        plan = plan_rescale("q", g, old, [0, 1, 2, 3], 4)
        assert _par(plan.new) == 4
        # minimal move set: the two ranges whose owner must change
        assert plan.moved_vnodes == VNODE_COUNT // 2
        # ranges that kept their owner are NOT in the move list
        for m in plan.moves:
            assert m.from_worker != m.to_worker
        # every new actor lands on a distinct worker per fragment
        for acts in plan.new.actors.values():
            workers = [a.worker for a in acts]
            assert len(set(workers)) == len(workers)

    def test_4_to_2_moves_exactly_half_the_ring(self):
        g = _agg_graph()
        old = FragmentScheduler().place("q", g, [0, 1, 2, 3],
                                        parallelism=4)
        plan = plan_rescale("q", g, old, [0, 1, 2, 3], 2)
        assert _par(plan.new) == 2
        assert plan.moved_vnodes == VNODE_COUNT // 2

    def test_same_parallelism_is_noop(self):
        g = _agg_graph()
        old = FragmentScheduler().place("q", g, [0, 1, 2, 3],
                                        parallelism=2)
        plan = plan_rescale("q", g, old, [0, 1, 2, 3], 2)
        assert plan.moves == [] and plan.moved_vnodes == 0
        assert plan.new.to_json() == old.to_json()

    def test_singleton_fragments_never_move(self):
        g = _agg_graph()
        old = FragmentScheduler().place("q", g, [0, 1, 2, 3],
                                        parallelism=2)
        plan = plan_rescale("q", g, old, [0, 1, 2, 3], 4)
        from risingwave_tpu.meta.fragment import shardable
        for fid, frag in g.fragments.items():
            if not shardable(frag):
                assert plan.new.actors[fid] == old.actors[fid]
                assert all(m.fragment_id != fid for m in plan.moves)

    def test_diff_merges_adjacent_ranges(self):
        g = _agg_graph()
        a = FragmentScheduler().place("q", g, [0, 1], parallelism=2)
        # same shape on swapped workers: the whole sharded ring moves as
        # two merged ranges (one per (src, dst) actor pair)
        import dataclasses
        swapped = dataclasses.replace(a)
        swapped.actors = {
            fid: [dataclasses.replace(x, worker={0: 1, 1: 0}[x.worker])
                  for x in acts]
            for fid, acts in a.actors.items()}
        moves = diff_placements(a, swapped)
        sharded_moves = [m for m in moves
                         if (m.vnode_end - m.vnode_start) < VNODE_COUNT]
        assert sum(m.width for m in sharded_moves) == VNODE_COUNT

    def test_rejects_bad_parallelism(self):
        g = _agg_graph()
        old = FragmentScheduler().place("q", g, [0, 1], parallelism=2)
        with pytest.raises(RescaleUnsupported):
            plan_rescale("q", g, old, [0, 1], 0)
        with pytest.raises(RescaleUnsupported):
            plan_rescale("q", g, old, [], 2)
        # refused loudly, never silently clamped to the worker count
        with pytest.raises(RescaleUnsupported, match="distinct workers"):
            plan_rescale("q", g, old, [0, 1], 4)


class TestAutoscalerPolicy:
    CFG = AutoscalerConfig(enabled=True, high_backlog=10,
                           high_permits_waited=5, hysteresis=3,
                           cooldown=4, scale_in_after=6,
                           min_parallelism=1, max_parallelism=8)

    def test_hysteresis_requires_consecutive_highs(self):
        a = Autoscaler(self.CFG)
        assert a.observe("j", 2, backlog=100) is None
        assert a.observe("j", 2, backlog=100) is None
        assert a.observe("j", 2, backlog=0, permits_waited=1) is None
        # the streak was broken: two more highs still aren't enough
        assert a.observe("j", 2, backlog=100) is None
        assert a.observe("j", 2, backlog=100) is None
        assert a.observe("j", 2, backlog=100) == 4

    def test_cooldown_blocks_second_decision(self):
        a = Autoscaler(self.CFG)
        for _ in range(2):
            a.observe("j", 2, backlog=100)
        assert a.observe("j", 2, backlog=100) == 4
        # high signals continue, but the cooldown holds...
        for _ in range(self.CFG.cooldown):
            assert a.observe("j", 4, backlog=100) is None
        # ...and once it expires a fresh streak is still required
        assert a.observe("j", 4, backlog=100) is None
        assert a.observe("j", 4, backlog=100) is None
        assert a.observe("j", 4, backlog=100) == 8

    def test_no_flapping_under_oscillating_load(self):
        a = Autoscaler(self.CFG)
        for i in range(40):
            target = a.observe("j", 2,
                               backlog=(100 if i % 2 == 0 else 0))
            assert target is None       # oscillation never sustains
        assert a.decisions == []

    def test_scale_in_is_lazy_and_halves(self):
        a = Autoscaler(self.CFG)
        for i in range(self.CFG.scale_in_after - 1):
            assert a.observe("j", 4) is None
        assert a.observe("j", 4) == 2

    def test_clamps_at_max_and_min(self):
        a = Autoscaler(self.CFG)
        for _ in range(3):
            t = a.observe("j", 8, backlog=100)
        assert t is None                # already at max: no decision
        b = Autoscaler(self.CFG)
        for i in range(self.CFG.scale_in_after):
            t = b.observe("j", 1)
        assert t is None                # already at min

    def test_live_worker_cap_blocks_unreachable_scale_out(self):
        # 2 live workers: a 2→4 decision could never execute
        # (plan_rescale needs 4 distinct workers), so the policy must
        # not fire it — no phantom decision churn every cooldown window
        a = Autoscaler(self.CFG)
        for _ in range(10):
            assert a.observe("j", 2, backlog=100, live_workers=2) is None
        assert a.decisions == [] and a.decisions_total == 0
        # with 3 live workers the cap still allows 2→3
        b = Autoscaler(self.CFG)
        for _ in range(2):
            b.observe("j", 2, backlog=100, live_workers=3)
        assert b.observe("j", 2, backlog=100, live_workers=3) == 3

    def test_decisions_total_is_monotonic_past_history_cap(self):
        cfg = AutoscalerConfig(enabled=True, high_backlog=10,
                               hysteresis=1, cooldown=0,
                               max_parallelism=1 << 80)
        a = Autoscaler(cfg)
        n = 0
        par = 2
        while n < 70:                    # history ring caps at 64
            t = a.observe("j", par, backlog=100)
            if t is not None:
                par, n = t, n + 1
        assert a.decisions_total == 70 and len(a.decisions) == 64
        assert a.status()["decisions_total"] == 70

    def test_failed_rescale_holds_cooldown(self):
        a = Autoscaler(self.CFG)
        for _ in range(2):
            a.observe("j", 2, backlog=100)
        assert a.observe("j", 2, backlog=100) == 4
        a.note_failed("j", "boom")
        st = a.status()["jobs"]["j"]
        assert st["cooldown"] >= self.CFG.cooldown
        assert st["last_error"] == "boom"


def cluster(workers=4, seed=42, data_dir=None, parallelism=2,
            **kw) -> Session:
    return Session(workers=workers, seed=seed, data_dir=data_dir,
                   source_chunk_capacity=CAP,
                   config=BuildConfig(fragment_parallelism=parallelism,
                                      **kw.pop("cfg", {})),
                   **kw)


def control_session(seed=42) -> Session:
    s = Session(seed=seed, source_chunk_capacity=CAP)
    s.run_sql(BID_DDL)
    s.run_sql(AGG)
    return s


class TestLiveRescale:
    def test_scale_out_2_to_4_bit_exact(self, tmp_path):
        """THE tentpole path: a spanning grouped-agg job rescales 2→4
        mid-stream. Only the changed half of the ring moves (migration
        metrics), worker processes stay up (same pids), output is
        bit-exact vs a no-rescale control, and the persisted placement
        carries the new parallelism."""
        s = cluster(data_dir=str(tmp_path / "d"))
        c = control_session()
        try:
            s.run_sql(BID_DDL)
            s.run_sql(AGG)
            assert "q" in s._spanning_specs
            pids = [w.proc.pid for w in s.workers]
            for _ in range(3):
                s.tick()
                c.tick()
            assert sorted(s.mv_rows("q")) == sorted(c.mv_rows("q"))
            out = s.rescale("q", 4)
            assert out["mode"] == "live-migration"
            assert out["parallelism"] == 4
            # only the changed vnode ranges moved
            assert out["moved_vnodes"] == VNODE_COUNT // 2
            for r in out["moved_ranges"]:
                assert r["from_worker"] != r["to_worker"]
            # live migration: no worker process was restarted
            assert [w.proc.pid for w in s.workers] == pids
            for _ in range(3):
                s.tick()
                c.tick()
            s.flush()
            c.flush()
            got = sorted(s.mv_rows("q"))
            assert got == sorted(c.mv_rows("q")) and got
            m = s.metrics()["autoscaler"]
            assert m["migrations"] == 1
            assert m["moved_vnodes"] == VNODE_COUNT // 2
            assert m["last_rescale"]["pause_ms"] > 0
            # handoff accounting balances: rows out == rows in
            h = m["handoff_rows"]
            assert sum(v["rows_out"] for v in h.values()) == \
                sum(v["rows_in"] for v in h.values()) > 0
            # the placement mutation went through the meta store
            persisted = s.meta.load_placement("q")
            assert _par(persisted) == 4
        finally:
            s.close()
            c.close()

    def test_serving_reads_stay_exact_across_rescale(self, tmp_path):
        """Batch SQL through the serving plane stays exactly-once across
        live migrations: cached pre-rescale entries are invalidated at
        the placement commit (their remote tasks name the OLD host set),
        and every per-host task ships its placed vnode range — an
        unrestricted scan would count handed-off leftover rows twice
        against the range's current owner."""
        s = cluster(data_dir=str(tmp_path / "d"))
        c = control_session()
        q1 = "SELECT count(*) AS groups FROM q"
        q2 = "SELECT auction, count(*) AS cnt FROM q GROUP BY auction"
        q3 = "SELECT auction, n FROM q WHERE n > 1"
        try:
            s.run_sql(BID_DDL)
            s.run_sql(AGG)
            for _ in range(2):
                s.tick()
                c.tick()
            # prime the serving cache BEFORE the rescale
            assert s.run_sql(q1) == c.run_sql(q1)
            assert sorted(s.run_sql(q3)) == sorted(c.run_sql(q3))
            for par in (4, 2):
                s.rescale("q", par)
                s.tick()
                c.tick()
                s.flush()
                c.flush()
                assert s.run_sql(q1) == c.run_sql(q1)
                assert sorted(s.run_sql(q2)) == sorted(c.run_sql(q2))
                assert sorted(s.run_sql(q3)) == sorted(c.run_sql(q3))
        finally:
            s.close()
            c.close()

    def test_rescale_remote_whole_job_refuses_loudly(self, tmp_path):
        """VERDICT #78: a round-robined whole-job placement cannot
        reschedule — that is now an explicit, documented refusal, not a
        silent ignore."""
        # one worker → span_plan refuses (fewer than two live workers)
        # → the MV deploys whole-job on the worker
        s = cluster(workers=1, data_dir=str(tmp_path / "d"))
        try:
            s.run_sql(BID_DDL)
            s.run_sql(AGG)
            assert "q" in s._remote_specs
            with pytest.raises(RescaleUnsupported) as ei:
                s.rescale("q", 2)
            assert "docs/scaling.md" in str(ei.value)
            # ...and the legacy reschedule path names the remediation
            from risingwave_tpu.frontend.session import SqlError
            with pytest.raises(SqlError) as ei2:
                s.reschedule("q")
            assert "rescale" in str(ei2.value)
        finally:
            s.close()

    def test_local_job_delegates_to_rebuild(self):
        """A session-local MV has no vnode-mapped placement: rescale
        delegates to the quiesce+rebuild reschedule under the new
        fragment parallelism (documented fallback, not live)."""
        s = Session(seed=42, source_chunk_capacity=CAP)
        c = control_session()
        try:
            s.run_sql(BID_DDL)
            s.run_sql(AGG)
            for _ in range(2):
                s.tick()
                c.tick()
            out = s.rescale("q", 2)
            assert out["mode"] == "local-rebuild"
            for _ in range(2):
                s.tick()
                c.tick()
            assert sorted(s.mv_rows("q")) == sorted(c.mv_rows("q"))
        finally:
            s.close()
            c.close()

    def test_kill9_mid_migration_rolls_back_fenced(self, tmp_path):
        """kill -9 of a worker between the state-ref export and the
        redeploy: the placement commit never happened, so the rescale
        ROLLS BACK — the generation bump fences anything the dead
        incarnation had in flight, the old placement redeploys from the
        untouched durable cut, and a later rescale succeeds."""
        from risingwave_tpu.common.config import FaultConfig
        from risingwave_tpu.common.failpoint import arm, disarm
        fc = FaultConfig(worker_epoch_timeout_s=60.0,
                         worker_request_timeout_s=60.0)
        s = cluster(data_dir=str(tmp_path / "d"), fault_config=fc)
        c = control_session()
        try:
            s.run_sql(BID_DDL)
            s.run_sql(AGG)
            for _ in range(3):
                s.tick()
                c.tick()
            victim = s._spanning_specs["q"]["workers"][0]
            gen0 = s._generation
            arm("rescale.migrate", victim.kill9, once=True)
            try:
                with pytest.raises(RuntimeError) as ei:
                    s.rescale("q", 4)
            finally:
                disarm("rescale.migrate")
            assert "rolled back" in str(ei.value)
            assert s._generation > gen0          # fenced
            # old placement still authoritative, in memory AND durably
            assert _par(s._spanning_specs["q"]["placement"]) == 2
            assert _par(s.meta.load_placement("q")) == 2
            for _ in range(3):
                s.tick()
                c.tick()
            s.flush()
            c.flush()
            assert sorted(s.mv_rows("q")) == sorted(c.mv_rows("q"))
            # the cluster healed: the same rescale now goes through
            out = s.rescale("q", 4)
            assert out["moved_vnodes"] == VNODE_COUNT // 2
            s.tick()
            c.tick()
            s.flush()
            c.flush()
            assert sorted(s.mv_rows("q")) == sorted(c.mv_rows("q"))
        finally:
            s.close()
            c.close()


@pytest.mark.slow
class TestLiveRescaleSlow:
    def test_scale_in_4_to_2_and_restart_redeploys(self, tmp_path):
        """4→2 scale-IN moves half the ring back, stays bit-exact, and
        a restarted session redeploys the persisted post-rescale
        placement (parallelism 2) — recovery and rescale persistence
        compose."""
        d = str(tmp_path / "d")
        s = cluster(data_dir=d, parallelism=4, seed=7)
        c = Session(seed=7, source_chunk_capacity=CAP)
        c.run_sql(BID_DDL)
        c.run_sql(AGG)
        try:
            s.run_sql(BID_DDL)
            s.run_sql(AGG)
            assert _par(s._spanning_specs["q"]["placement"]) == 4
            for _ in range(3):
                s.tick()
                c.tick()
            out = s.rescale("q", 2)
            assert out["mode"] == "live-migration"
            assert out["moved_vnodes"] == VNODE_COUNT // 2
            for _ in range(2):
                s.tick()
                c.tick()
            s.flush()
            c.flush()
            assert sorted(s.mv_rows("q")) == sorted(c.mv_rows("q"))
            s.close()
            s = cluster(data_dir=d, parallelism=4, seed=7)
            assert _par(s._spanning_specs["q"]["placement"]) == 2
            for _ in range(2):
                s.tick()
                c.tick()
            s.flush()
            c.flush()
            assert sorted(s.mv_rows("q")) == sorted(c.mv_rows("q"))
        finally:
            s.close()
            c.close()

    def test_kill9_after_commit_rolls_forward(self, tmp_path):
        """kill -9 of a worker AFTER the placement commit: the new
        placement is authoritative, so recovery rolls FORWARD — the job
        converges at the new parallelism, bit-exact."""
        from risingwave_tpu.common.config import FaultConfig
        from risingwave_tpu.common.failpoint import arm, disarm
        fc = FaultConfig(worker_epoch_timeout_s=60.0,
                         worker_request_timeout_s=60.0)
        s = cluster(data_dir=str(tmp_path / "d"), fault_config=fc)
        c = control_session()
        try:
            s.run_sql(BID_DDL)
            s.run_sql(AGG)
            for _ in range(3):
                s.tick()
                c.tick()
            victim = s._spanning_specs["q"]["workers"][0]
            arm("rescale.commit", victim.kill9, once=True)
            try:
                s.rescale("q", 4)   # rolls forward internally
            finally:
                disarm("rescale.commit")
            assert _par(s._spanning_specs["q"]["placement"]) == 4
            assert _par(s.meta.load_placement("q")) == 4
            for _ in range(3):
                s.tick()
                c.tick()
            s.flush()
            c.flush()
            assert sorted(s.mv_rows("q")) == sorted(c.mv_rows("q"))
        finally:
            s.close()
            c.close()

    def test_q5_rescale_2_to_4_bit_exact(self, tmp_path):
        """The ROADMAP acceptance shape: the spanning q5 graph (two
        sharded hop-window aggs feeding a join) rescales 2→4 workers
        mid-stream — only the sharded fragments' changed ranges move —
        and stays bit-exact vs a no-rescale control."""
        s = cluster(data_dir=str(tmp_path / "d"))
        c = Session(seed=42, source_chunk_capacity=CAP)
        c.run_sql(BID_DDL)
        c.run_sql(Q5)
        try:
            s.run_sql(BID_DDL)
            s.run_sql(Q5)
            assert "q5" in s._spanning_specs
            for _ in range(3):
                s.tick()
                c.tick()
            out = s.rescale("q5", 4)
            assert out["mode"] == "live-migration"
            # every sharded agg fragment went to 4 actors and moved
            # exactly half ITS ring — singletons moved nothing
            sharded = [acts for acts in
                       s._spanning_specs["q5"]["placement"].actors
                       .values() if len(acts) == 4]
            assert len(sharded) >= 2
            assert out["moved_vnodes"] == \
                len(sharded) * (VNODE_COUNT // 2)
            for _ in range(3):
                s.tick()
                c.tick()
            s.flush()
            c.flush()
            got = sorted(s.mv_rows("q5"))
            assert got == sorted(c.mv_rows("q5")) and got
        finally:
            s.close()
            c.close()

    def test_autoscaler_scales_out_and_does_not_flap(self, tmp_path):
        """End-to-end policy loop: a traffic spike over a tiny permit
        budget drives permits_waited up; the autoscaler live-rescales
        2→4 after its hysteresis, then holds steady when the load
        subsides (cooldown + lazy scale-in = no flapping)."""
        acfg = AutoscalerConfig(enabled=True, high_permits_waited=1,
                                hysteresis=2, cooldown=6,
                                scale_in_after=64, max_parallelism=4)
        s = cluster(data_dir=str(tmp_path / "d"), seed=3,
                    autoscaler_config=acfg,
                    cfg={"exchange_permits": 2})
        try:
            s.run_sql(BID_DDL)
            s.run_sql(AGG)
            spec = s._spanning_specs["q"]
            for _ in range(2):
                s.tick()
            s.set_source_rate(8)
            for _ in range(12):
                s.tick()
                if _par(spec["placement"]) == 4:
                    break
            assert _par(spec["placement"]) == 4, \
                s.autoscaler.status()
            assert len(s.autoscaler.decisions) == 1
            s.set_source_rate(1)
            for _ in range(8):
                s.tick()
            assert _par(spec["placement"]) == 4
            assert len(s.autoscaler.decisions) == 1   # no flap
        finally:
            s.close()

    def test_sim_traffic_spike_scenario(self, tmp_path):
        """The seeded sim scenario end to end: autonomous 2→4 under a
        load spike, minimal move set, exactly-once audit green, no flap
        on subside (python -m risingwave_tpu.sim --traffic-spike)."""
        from risingwave_tpu.sim import run_traffic_spike
        out = run_traffic_spike(seed=7, data_dir=str(tmp_path / "d"))
        assert out["parallelism"] == 4
        assert out["moved_vnodes"] == VNODE_COUNT // 2
        assert all(out["audit"].values()), out["audit"]
        assert len(out["decisions"]) == 1
