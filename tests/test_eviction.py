"""HBM eviction to the cold tier (VERDICT r3 item 5).

Device agg state becomes a CACHE over the state table: at checkpoints a
grouped agg holding more live groups than its ``hbm_group_budget`` evicts
the coldest (LRU) to the durable tier; an evicted key arriving again
faults its stored lanes back in and the flush emits an exact U-/U+ pair
(reference: ManagedLruCache over StateTables,
src/stream/src/cache/managed_lru.rs).

The headline criterion: a run whose total group count is >4x the device
budget completes with results identical to an unbudgeted run.
"""

import pytest

from risingwave_tpu.frontend import Session
from risingwave_tpu.frontend.build import BuildConfig


def _mv_run(cfg, n_batches=8, groups=256, revisit_every=3):
    """Feed batches of rows spread over ``groups`` distinct keys, with a
    periodic revisit of the earliest (coldest) keys so fault-in happens."""
    s = Session(config=cfg, checkpoint_frequency=2)
    s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, g BIGINT, v BIGINT)")
    s.run_sql("CREATE MATERIALIZED VIEW m AS "
              "SELECT g, count(*) AS n, sum(v) AS sv, min(v) AS lo "
              "FROM t GROUP BY g")
    k = 0
    per = groups // n_batches
    for b in range(n_batches):
        vals = []
        for i in range(per):
            g = b * per + i
            vals.append(f"({k}, {g}, {g * 10 + 1})")
            k += 1
        if b % revisit_every == 2:
            # touch the very first (long-cold, likely evicted) groups
            for g in range(4):
                vals.append(f"({k}, {g}, {g * 10 + 7})")
                k += 1
        s.run_sql(f"INSERT INTO t VALUES {', '.join(vals)}")
        s.flush()
    rows = sorted(s.mv_rows("m"))
    s.close()
    return rows


class TestAggEviction:
    def test_4x_budget_equals_unbudgeted(self):
        base = _mv_run(BuildConfig())
        budget = BuildConfig(agg_hbm_budget=60)   # 256 groups ≈ 4.3x budget
        got = _mv_run(budget)
        assert got == base and len(base) == 256

    def test_evicted_key_faults_back_in_exactly(self):
        """Direct executor-level check: eviction happens, the key's later
        rows merge with the stored lanes, and no duplicate insert reaches
        the changelog (downstream totals stay exact)."""
        base = _mv_run(BuildConfig(), n_batches=6, groups=120,
                       revisit_every=2)
        got = _mv_run(BuildConfig(agg_hbm_budget=30), n_batches=6,
                      groups=120, revisit_every=2)
        assert got == base

    def test_float_group_keys_survive_eviction(self):
        """Evicted-key identity must preserve float group keys (r4 review:
        int() coercion collided 2.3/2.7 and broke fault-in)."""
        s = Session(config=BuildConfig(agg_hbm_budget=20),
                    checkpoint_frequency=2)
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, g DOUBLE, "
                  "v BIGINT)")
        base = Session(checkpoint_frequency=2)
        base.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, g DOUBLE, "
                     "v BIGINT)")
        for sess in (s, base):
            sess.run_sql("CREATE MATERIALIZED VIEW m AS "
                         "SELECT g, count(*) AS n, sum(v) AS sv "
                         "FROM t GROUP BY g")
        k = 0
        for b in range(4):
            vals = ", ".join(
                f"({k + i}, {b * 25 + i}.5, {i})" for i in range(25))
            k += 25
            for sess in (s, base):
                sess.run_sql(f"INSERT INTO t VALUES {vals}")
                sess.flush()
        # revisit the earliest (evicted) float keys
        for sess in (s, base):
            sess.run_sql("INSERT INTO t VALUES (9001, 0.5, 100), "
                         "(9002, 1.5, 200)")
            sess.flush()
        assert sorted(s.mv_rows("m")) == sorted(base.mv_rows("m"))
        s.close()
        base.close()

    def test_recovery_with_more_groups_than_budget(self, tmp_path):
        d = str(tmp_path / "db")
        cfg = BuildConfig(agg_hbm_budget=40)
        s = Session(config=cfg, data_dir=d, checkpoint_frequency=2)
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, g BIGINT, "
                  "v BIGINT)")
        s.run_sql("CREATE MATERIALIZED VIEW m AS "
                  "SELECT g, count(*) AS n, sum(v) AS sv FROM t GROUP BY g")
        k = 0
        for b in range(4):
            vals = ", ".join(f"({k + i}, {b * 50 + i % 50}, {i})"
                             for i in range(50))
            k += 50
            s.run_sql(f"INSERT INTO t VALUES {vals}")
            s.flush()
        want = sorted(s.mv_rows("m"))
        assert len(want) == 200        # 5x the budget in the durable tier
        s.close()

        s2 = Session(config=cfg, data_dir=d, checkpoint_frequency=2)
        assert sorted(s2.mv_rows("m")) == want
        # keeps maintaining after recovery, including cold keys
        s2.run_sql("INSERT INTO t VALUES (9001, 0, 5), (9002, 199, 5)")
        s2.flush()
        after = {r[0]: r for r in s2.mv_rows("m")}
        w = {r[0]: r for r in want}
        assert after[0][1] == w[0][1] + 1
        assert after[199][2] == w[199][2] + 5
        s2.close()


def _join_run(cfg, n_keys=120, revisit=True):
    """Insert rows on both sides over ``n_keys`` join keys, revisiting the
    earliest (coldest, likely evicted) keys with inserts AND deletes so
    fault-in must restore both arenas before applying them."""
    s = Session(config=cfg, checkpoint_frequency=2)
    s.run_sql("CREATE TABLE l (k BIGINT PRIMARY KEY, j BIGINT, a BIGINT)")
    s.run_sql("CREATE TABLE r (k BIGINT PRIMARY KEY, j BIGINT, b BIGINT)")
    s.run_sql("CREATE MATERIALIZED VIEW jm AS "
              "SELECT l.j AS j, l.a AS a, r.b AS b "
              "FROM l JOIN r ON l.j = r.j")
    per = 20
    for b in range(n_keys // per):
        lv = ", ".join(f"({b * per + i}, {b * per + i}, {i})"
                       for i in range(per))
        rv = ", ".join(f"({b * per + i}, {b * per + i}, {i * 2})"
                       for i in range(0, per, 2))
        s.run_sql(f"INSERT INTO l VALUES {lv}")
        s.run_sql(f"INSERT INTO r VALUES {rv}")
        s.flush()
    if revisit:
        # cold keys: new match on key 0, delete the match on key 2,
        # second left row on key 4 (degree > 1 after fault-in)
        s.run_sql("INSERT INTO r VALUES (9001, 1, 77)")
        s.run_sql("DELETE FROM r WHERE k = 2")
        s.run_sql("INSERT INTO l VALUES (9002, 4, 55)")
        s.flush()
    rows = sorted(s.mv_rows("jm"))
    caps = _join_capacities(s, "jm")
    s.close()
    return rows, caps


def _join_capacities(s, mv):
    caps = []
    stack = [s.jobs[mv].pipeline]
    while stack:
        ex = stack.pop()
        if ex is None:
            continue
        if type(ex).__name__ == "HashJoinExecutor":
            caps.append(ex.core.capacity)
        for at in ("input", "left", "right"):
            stack.append(getattr(ex, at, None))
    return caps


class TestJoinEviction:
    def test_key_space_larger_than_arena_bounded_hbm(self):
        """120 join keys through a 128-slot arena with a 32-key budget: the
        arena must NOT grow (bounded HBM — live keys stay near the budget)
        and results must equal the unbudgeted run — including
        deletes/inserts on faulted-back keys."""
        base, _ = _join_run(BuildConfig())
        got, caps = _join_run(BuildConfig(join_key_capacity=128,
                                          join_hbm_budget=32))
        assert got == base and len(base) > 0
        assert caps == [128]    # eviction kept the arena at its birth size

    def test_join_recovery_with_more_keys_than_budget(self, tmp_path):
        d = str(tmp_path / "db")
        cfg = BuildConfig(join_key_capacity=128, join_hbm_budget=32)
        s = Session(config=cfg, data_dir=d, checkpoint_frequency=1)
        s.run_sql("CREATE TABLE l (k BIGINT PRIMARY KEY, j BIGINT, "
                  "a BIGINT)")
        s.run_sql("CREATE TABLE r (k BIGINT PRIMARY KEY, j BIGINT, "
                  "b BIGINT)")
        s.run_sql("CREATE MATERIALIZED VIEW jm AS "
                  "SELECT l.j AS j, l.a AS a, r.b AS b "
                  "FROM l JOIN r ON l.j = r.j")
        for b in range(6):
            lv = ", ".join(f"({b * 20 + i}, {b * 20 + i}, {i})"
                           for i in range(20))
            s.run_sql(f"INSERT INTO l VALUES {lv}")
            s.run_sql(f"INSERT INTO r VALUES {lv}")
            s.flush()
        want = sorted(s.mv_rows("jm"))
        assert len(want) == 120
        s.close()

        s2 = Session(config=cfg, data_dir=d, checkpoint_frequency=1)
        assert sorted(s2.mv_rows("jm")) == want
        # cold keys still join correctly after recovery
        s2.run_sql("INSERT INTO l VALUES (9001, 0, 42)")
        s2.run_sql("DELETE FROM r WHERE k = 1")
        s2.flush()
        after = sorted(s2.mv_rows("jm"))
        assert (0, 42, 0) in after
        assert len(after) == 120    # +1 new match, -1 deleted match
        s2.close()
