"""Fused single-dispatch epochs (ops/fused_epoch.py): one lax.scan doing
generate → project → aggregate must produce EXACTLY the state the
executor-path per-chunk apply produces over the same chunks."""

import jax
import jax.numpy as jnp
import numpy as np

from risingwave_tpu.common import INT64, TIMESTAMP
from risingwave_tpu.connector import BID_SCHEMA, NexmarkConfig
from risingwave_tpu.connector.nexmark import DeviceBidGenerator
from risingwave_tpu.expr import Literal, call, col
from risingwave_tpu.expr.agg import agg as agg_call, count_star
from risingwave_tpu.ops.fused_epoch import fused_source_agg_epoch
from risingwave_tpu.stream import HashAggExecutor, ProjectExecutor
from risingwave_tpu.stream.source import MockSource

CAP = 256


def _pipeline():
    exprs = [
        call("tumble_start", col(5, TIMESTAMP), Literal(1_000_000, INT64)),
        col(0, INT64),
        col(2, INT64),
    ]
    proj = ProjectExecutor(MockSource(BID_SCHEMA, []), exprs,
                           names=("ws", "auction", "price"))
    agg = HashAggExecutor(proj, [0, 1],
                          [count_star(), agg_call("max", 2, INT64)],
                          table_capacity=1 << 12, out_capacity=CAP)
    return exprs, agg


def test_fused_epoch_matches_per_chunk_apply():
    exprs, agg = _pipeline()
    gen = DeviceBidGenerator(NexmarkConfig(chunk_capacity=CAP))
    fused = fused_source_agg_epoch(gen.chunk_fn(), exprs, agg.core, CAP)
    key = jax.random.PRNGKey(5)
    k = 8

    fused_state = fused(agg.core.init_state(), jnp.int64(0), key, k)

    # executor-equivalent fold: same chunks, one apply per chunk. The agg
    # input keeps the full bid schema with (ws, auction) projected into
    # cols 0/1 — exactly what the fused body builds.
    fn = gen.chunk_fn()
    st = agg.core.init_state()
    for i in range(k):
        ch = fn(jnp.int64(i * CAP), jax.random.fold_in(key, i))
        projected = ch.with_columns(tuple(e.eval(ch) for e in exprs))
        st = agg._apply(st, projected, None, None)

    np.testing.assert_array_equal(np.asarray(fused_state.table.occupied),
                                  np.asarray(st.table.occupied))
    for a, b in zip(fused_state.lanes, st.lanes):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(fused_state.table.key_data, st.table.key_data):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # sanity: state is non-trivial (groups actually accumulated)
    assert int(np.asarray(fused_state.table.occupied).sum()) > 10


def test_fused_epoch_is_one_dispatch():
    """The epoch function must lower to a single jitted computation whose
    trace contains the scan (no per-chunk python loop)."""
    exprs, agg = _pipeline()
    gen = DeviceBidGenerator(NexmarkConfig(chunk_capacity=CAP))
    fused = fused_source_agg_epoch(gen.chunk_fn(), exprs, agg.core, CAP)
    lowered = fused.lower(agg.core.init_state(), jnp.int64(0),
                          jax.random.PRNGKey(0), 4)
    text = lowered.as_text()
    assert "while" in text or "scan" in text   # the epoch loop is ON device
