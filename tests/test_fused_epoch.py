"""Fused single-dispatch epochs (ops/fused_epoch.py): one lax.scan doing
generate → project → aggregate must produce EXACTLY the state the
executor-path per-chunk apply produces over the same chunks."""

import jax
import jax.numpy as jnp
import numpy as np

from risingwave_tpu.common import INT64, TIMESTAMP
from risingwave_tpu.connector import BID_SCHEMA, NexmarkConfig
from risingwave_tpu.connector.nexmark import DeviceBidGenerator
from risingwave_tpu.expr import Literal, call, col
from risingwave_tpu.expr.agg import agg as agg_call, count_star
from risingwave_tpu.ops.fused_epoch import fused_source_agg_epoch
from risingwave_tpu.stream import HashAggExecutor, ProjectExecutor
from risingwave_tpu.stream.source import MockSource

CAP = 256


def _pipeline():
    exprs = [
        call("tumble_start", col(5, TIMESTAMP), Literal(1_000_000, INT64)),
        col(0, INT64),
        col(2, INT64),
    ]
    proj = ProjectExecutor(MockSource(BID_SCHEMA, []), exprs,
                           names=("ws", "auction", "price"))
    agg = HashAggExecutor(proj, [0, 1],
                          [count_star(), agg_call("max", 2, INT64)],
                          table_capacity=1 << 12, out_capacity=CAP)
    return exprs, agg


def test_fused_epoch_matches_per_chunk_apply():
    exprs, agg = _pipeline()
    gen = DeviceBidGenerator(NexmarkConfig(chunk_capacity=CAP))
    fused = fused_source_agg_epoch(gen.chunk_fn(), exprs, agg.core, CAP)
    key = jax.random.PRNGKey(5)
    k = 8

    fused_state = fused(agg.core.init_state(), jnp.int64(0), key, k)

    # executor-equivalent fold: same chunks, one apply per chunk. The agg
    # input keeps the full bid schema with (ws, auction) projected into
    # cols 0/1 — exactly what the fused body builds.
    fn = gen.chunk_fn()
    st = agg.core.init_state()
    for i in range(k):
        ch = fn(jnp.int64(i * CAP), jax.random.fold_in(key, i))
        projected = ch.with_columns(tuple(e.eval(ch) for e in exprs))
        st = agg._apply(st, projected, None, None)

    np.testing.assert_array_equal(np.asarray(fused_state.table.occupied),
                                  np.asarray(st.table.occupied))
    for a, b in zip(fused_state.lanes, st.lanes):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(fused_state.table.key_data, st.table.key_data):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # sanity: state is non-trivial (groups actually accumulated)
    assert int(np.asarray(fused_state.table.occupied).sum()) > 10


def test_fused_epoch_is_one_dispatch():
    """The epoch function must lower to a single jitted computation whose
    trace contains the scan (no per-chunk python loop)."""
    exprs, agg = _pipeline()
    gen = DeviceBidGenerator(NexmarkConfig(chunk_capacity=CAP))
    fused = fused_source_agg_epoch(gen.chunk_fn(), exprs, agg.core, CAP)
    lowered = fused.lower(agg.core.init_state(), jnp.int64(0),
                          jax.random.PRNGKey(0), 4)
    text = lowered.as_text()
    assert "while" in text or "scan" in text   # the epoch loop is ON device


# ---------------------------------------------------------------------------
# q7: fused source → project → bucketed interval join (the second fusion
# surface; ops/interval_join.py + fused_source_join_epoch)
# ---------------------------------------------------------------------------

from risingwave_tpu.ops.fused_epoch import fused_source_join_epoch
from risingwave_tpu.ops.interval_join import IntervalJoinCore

Q7_WINDOW = 5_000


def _q7_parts(n_buckets=512, lane_width=64):   # ~50 bids per 5ms window
    exprs = [
        call("tumble_start", col(5, TIMESTAMP), Literal(Q7_WINDOW, INT64)),
        col(0, INT64),
        col(2, INT64),
    ]
    from risingwave_tpu.common import Schema, Field
    probe_schema = Schema((
        Field("window_start", TIMESTAMP), Field("auction", INT64),
        Field("price", INT64)))
    core = IntervalJoinCore(probe_schema, ts_col=0, val_col=2,
                            window_us=Q7_WINDOW, n_buckets=n_buckets,
                            lane_width=lane_width)
    return exprs, core


def test_fused_join_epoch_matches_per_chunk_apply():
    exprs, core = _q7_parts()
    gen = DeviceBidGenerator(NexmarkConfig(chunk_capacity=CAP))
    fused = fused_source_join_epoch(gen.chunk_fn(), exprs, core, CAP)
    key = jax.random.PRNGKey(11)
    k = 8

    state, probe_out, del_m, ins_m, old_emitted, packed = fused(
        core.init_state(), jnp.int64(0), key, k)

    # sequential fold: same chunks, one core step per chunk, then the
    # same flush — must be bit-identical
    fn = gen.chunk_fn()
    st = core.init_state()
    outs = []
    for i in range(k):
        ch = fn(jnp.int64(i * CAP), jax.random.fold_in(key, i))
        projected = ch.with_columns(tuple(e.eval(ch) for e in exprs))
        st, out = jax.jit(core.apply_chunk)(st, projected)
        outs.append(out)
    old2 = st.emitted_max
    del2, ins2, packed2 = jax.jit(core.flush_plan)(st)
    st = jax.jit(core.finish_flush)(st)

    np.testing.assert_array_equal(np.asarray(del_m), np.asarray(del2))
    np.testing.assert_array_equal(np.asarray(ins_m), np.asarray(ins2))
    np.testing.assert_array_equal(np.asarray(old_emitted), np.asarray(old2))
    np.testing.assert_array_equal(np.asarray(packed[:4]),
                                  np.asarray(packed2))
    assert int(packed[4]) == sum(
        int(np.asarray(out.vis).sum()) for out in outs)
    for i, out in enumerate(outs):
        np.testing.assert_array_equal(np.asarray(probe_out.vis[i]),
                                      np.asarray(out.vis))
        for ca, cb in zip(probe_out.columns, out.columns):
            np.testing.assert_array_equal(np.asarray(ca.data[i]),
                                          np.asarray(cb.data))
    for a, b in zip(jax.tree_util.tree_leaves(state),
                    jax.tree_util.tree_leaves(st)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # sanity: the epoch produced real state + some flush emissions
    assert not bool(state.lane_overflow)
    assert int(np.asarray(state.cur_cnt).sum()) == k * CAP
    assert int(packed[0]) > 0


def test_fused_join_epoch_is_one_dispatch():
    exprs, core = _q7_parts()
    gen = DeviceBidGenerator(NexmarkConfig(chunk_capacity=CAP))
    fused = fused_source_join_epoch(gen.chunk_fn(), exprs, core, CAP)
    lowered = fused.lower(core.init_state(), jnp.int64(0),
                          jax.random.PRNGKey(0), 4)
    text = lowered.as_text()
    assert "while" in text or "scan" in text   # the epoch loop is ON device


# ---------------------------------------------------------------------------
# dispatch-count regression: the fused q5/q7 epochs stay ONE dispatch per
# epoch, and per-epoch dispatch totals do not scale with k (a reintroduced
# per-chunk ladder would) — common/dispatch_count.py
# ---------------------------------------------------------------------------

from risingwave_tpu.common.dispatch_count import count_dispatches

Q5_EPOCH_FN = "fused_source_agg_epoch.<locals>.epoch"
Q7_EPOCH_FN = "fused_source_join_epoch.<locals>.epoch"


def _nongather_total(counter):
    return sum(n for name, n in counter.counts.items()
               if "gather" not in name)


def test_q5_fused_epoch_dispatch_count():
    with count_dispatches() as c:
        exprs = [
            call("tumble_start", col(5, TIMESTAMP),
                 Literal(1_000_000, INT64)),
            col(0, INT64),
        ]
        proj = ProjectExecutor(MockSource(BID_SCHEMA, []), exprs,
                               names=("ws", "auction"))
        agg = HashAggExecutor(proj, [0, 1], [count_star()],
                              table_capacity=1 << 12, out_capacity=2048)
        gen = DeviceBidGenerator(NexmarkConfig(chunk_capacity=CAP))
        fused = fused_source_agg_epoch(gen.chunk_fn(), exprs, agg.core,
                                       CAP)

        def epoch(state, start, batch_no, k):
            key = jax.random.fold_in(jax.random.PRNGKey(17), batch_no)
            state = fused(state, jnp.int64(start), key, k)
            packed, rank = agg._probe(state)
            n_dirty, overflow, _ = (int(x) for x in jax.device_get(packed))
            assert not overflow
            lo = 0
            while lo < n_dirty:
                agg._gather(state, rank, jnp.int64(lo))
                lo += agg.core.groups_per_chunk
            return agg._finish(state)

        state = epoch(agg.core.init_state(), 0, 0, 4)   # compile
        c.reset()
        state = epoch(state, 4 * CAP, 1, 4)
        assert c.counts[Q5_EPOCH_FN] == 1   # ingest = ONE dispatch/epoch
        n4 = _nongather_total(c)
        c.reset()
        state = epoch(state, 8 * CAP, 2, 8)
        assert c.counts[Q5_EPOCH_FN] == 1
        n8 = _nongather_total(c)
        assert n4 == n8   # per-epoch dispatches independent of k


def test_q7_fused_epoch_dispatch_count():
    with count_dispatches() as c:
        exprs, core = _q7_parts()
        gen = DeviceBidGenerator(NexmarkConfig(chunk_capacity=CAP))
        fused = fused_source_join_epoch(gen.chunk_fn(), exprs, core, CAP)
        gather = jax.jit(core.gather_flush,
                         static_argnames=("out_capacity",))

        def epoch(state, start, batch_no, k):
            key = jax.random.fold_in(jax.random.PRNGKey(3), batch_no)
            (state, probe_out, del_m, ins_m, old_emitted,
             packed) = fused(state, jnp.int64(start), key, k)
            n_units, ovf, clobber, sawdel, _n_probe = (
                int(x) for x in jax.device_get(packed))
            assert not (ovf or clobber or sawdel)
            lo = 0
            while lo < n_units:
                gather(state, del_m, ins_m, old_emitted, jnp.int64(lo),
                       out_capacity=2048)
                lo += 2048
            return state

        state = epoch(core.init_state(), 0, 0, 4)   # compile
        c.reset()
        state = epoch(state, 4 * CAP, 1, 4)
        assert c.counts[Q7_EPOCH_FN] == 1   # whole pipeline: ONE dispatch
        n4 = _nongather_total(c)
        c.reset()
        state = epoch(state, 8 * CAP, 2, 8)
        assert c.counts[Q7_EPOCH_FN] == 1
        n8 = _nongather_total(c)
        assert n4 == n8
