"""Barrier pipelining + mutations (VERDICT r2 item 5)."""

import time

import pytest

from risingwave_tpu.frontend import Session
from risingwave_tpu.stream.materialize import MaterializeExecutor
from risingwave_tpu.stream.message import MutationKind

DDL = """
CREATE SOURCE bid (auction BIGINT, bidder BIGINT, price BIGINT,
  channel VARCHAR, url VARCHAR, date_time TIMESTAMP, extra VARCHAR)
WITH (connector = 'nexmark', nexmark_table = 'bid')
"""
MV = "CREATE MATERIALIZED VIEW q AS SELECT auction, COUNT(*) AS c FROM bid GROUP BY auction"


def make(k=1):
    s = Session(source_chunk_capacity=64, in_flight_barriers=k)
    s.run_sql(DDL)
    s.run_sql(MV)
    return s


def test_inflight_structure_and_equivalence():
    s4 = make(k=4)
    for _ in range(3):
        s4.tick()
    # three barriers outstanding, none awaited yet
    assert len(s4._inflight) == 3
    assert s4.epoch < s4._injected
    rows4 = sorted(s4.mv_rows("q"))     # read drains in-flight epochs
    assert not s4._inflight
    assert s4.epoch == s4._injected

    s1 = make(k=1)
    for _ in range(3):
        s1.tick()
    assert not s1._inflight
    assert sorted(s1.mv_rows("q")) == rows4
    assert len(rows4) > 0


def test_pipelining_overlaps_session_work(monkeypatch):
    """Source generation (session thread) overlaps job processing when
    barriers are pipelined: wall time approaches max(G, J) per epoch rather
    than G + J."""
    GEN_MS = JOB_MS = 0.06
    orig_barrier = MaterializeExecutor.on_barrier

    async def slow_barrier(self, barrier):
        import asyncio
        await asyncio.sleep(JOB_MS)
        async for x in orig_barrier(self, barrier):
            yield x

    monkeypatch.setattr(MaterializeExecutor, "on_barrier", slow_barrier)

    def timed(k, n=8):
        s = make(k=k)
        for _ in range(2):          # compile warmup outside the timed region
            s.tick(checkpoint=False)
        s._drain_inflight()
        gen0 = s.feeds[0].generator
        s.feeds[0].generator = lambda: (time.sleep(GEN_MS), gen0())[1]
        t0 = time.perf_counter()
        for _ in range(n):
            s.tick(checkpoint=False)
        s._drain_inflight()
        return time.perf_counter() - t0

    serial = timed(1)
    pipelined = timed(4)
    # serial pays G+J per epoch, pipelined ~max(G,J); demand a robust win
    assert pipelined < serial * 0.85, (pipelined, serial)


def test_pause_resume_mutations():
    s = make()
    for _ in range(2):
        s.tick()
    n0 = len(s.mv_rows("q"))
    assert n0 > 0
    total0 = sum(r[1] for r in s.mv_rows("q"))
    s.pause()
    assert s.paused
    for _ in range(3):
        s.tick()
    assert sum(r[1] for r in s.mv_rows("q")) == total0  # no new data
    s.resume()
    for _ in range(2):
        s.tick()
    assert sum(r[1] for r in s.mv_rows("q")) > total0


def test_add_mutation_on_new_mv():
    s = make()
    s.tick()
    s.run_sql("CREATE MATERIALIZED VIEW q2 AS SELECT auction, c FROM q")
    assert s._pending_mutation is not None
    assert s._pending_mutation.kind == MutationKind.ADD
    assert s._pending_mutation.payload == "q2"
    s.tick()
    assert s._pending_mutation is None   # announced on the barrier
    s.tick()
    assert sorted(s.mv_rows("q2")) == sorted(
        (r[0], r[1]) for r in s.mv_rows("q"))


def test_stop_on_drop():
    s = make()
    s.tick()
    job = s.jobs["q"]
    s.run_sql("DROP MATERIALIZED VIEW q")
    assert "q" not in s.jobs
    assert job._task.done()
