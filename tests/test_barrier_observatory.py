"""Barrier observatory (ISSUE 16): per-barrier lifecycle ledger,
stuck-barrier blame, and the SQL-queryable telemetry catalog.

Acceptance pinned here:
  * every completed epoch gets a waterfall record whose conductor-stage
    sum reconciles with the session's barrier-latency percentiles, with
    ZERO added dispatches at pipeline_depth 1 and 2;
  * a 2-worker spanning job's federated record carries both workers'
    collect/storage stages, matching the single-process record
    stage-for-stage on the conductor side;
  * a chaos-partitioned exchange edge is named — consumer actor + link —
    by ``Session.barrier_blame()``, ``ctl trace barrier --inflight`` AND
    ``SELECT * FROM rw_catalog.rw_barrier_inflight`` over pgwire, all
    BEFORE the epoch-deadline recovery path fires;
  * rw_catalog system relations never touch the serving plan cache;
  * the slow-epoch capture ring is config-sized and attaches the
    offending barrier's waterfall record.
"""

import asyncio
import json
import os
import tempfile
import types

import pytest

from risingwave_tpu.common.barrier_ledger import (
    ALL_STAGES, BarrierLedger, CONDUCTOR_STAGES, StageEventLog,
)
from risingwave_tpu.frontend import Session

CAP = 64

BID_DDL = """CREATE SOURCE bid (auction BIGINT, bidder BIGINT,
    price BIGINT, channel VARCHAR, url VARCHAR, date_time TIMESTAMP,
    extra VARCHAR) WITH (connector = 'nexmark', nexmark_table = 'bid')"""
AGG = ("CREATE MATERIALIZED VIEW q AS SELECT auction, count(*) AS n, "
       "max(price) AS mx FROM bid GROUP BY auction")


# -- unit: the ledger + stage-event log ---------------------------------------


class TestStageEventLog:
    def test_outbox_retains_until_acked(self):
        log = StageEventLog()
        log.record(5, "storage_prepare", 1.5)
        seq1, ev1 = log.drain_outbox(None)
        assert [e["stage"] for e in ev1] == ["storage_prepare"]
        # unacked: the batch is retained and re-shipped
        seq2, ev2 = log.drain_outbox(None)
        assert seq2 == seq1 and ev2 == ev1
        # acked: the batch clears; no fresh events → same seq, empty
        seq3, ev3 = log.drain_outbox(seq1)
        assert seq3 == seq1 and ev3 == []

    def test_seq_bumps_only_on_fresh_events(self):
        log = StageEventLog()
        s0, _ = log.drain_outbox(None)
        log.record(1, "sink_deliver", 0.2)
        s1, ev = log.drain_outbox(s0)
        assert s1 == s0 + 1 and len(ev) == 1


class TestBarrierLedger:
    def test_waterfall_assembly_and_late_attach(self):
        led = BarrierLedger(capacity=4)
        led.begin(7, True, 123.0)
        led.stage(7, "collect", 2.0)
        rec = led.finish(7, 10.0, "ok")
        assert rec["total_ms"] == 10.0 and rec["result"] == "ok"
        # late worker events attach to the SEALED ring record by epoch
        led.ingest_events([{"epoch": 7, "stage": "worker_collect",
                            "ms": 3.25}], worker=1)
        got = led.get(7)
        assert got["stages"]["worker_collect"] == 3.25
        assert got["workers"][1] == {"worker_collect": 3.25}
        assert got["workers"][-1] == {"collect": 2.0}

    def test_ring_eviction_and_percentiles(self):
        led = BarrierLedger(capacity=2)
        for e in (1, 2, 3):
            led.begin(e, False, 0.0)
            led.stage(e, "collect", float(e))
            led.finish(e, float(e), "ok")
        assert len(led) == 2
        assert led.get(1) is None          # evicted with its index
        pct = led.stage_percentiles()["collect"]
        assert pct["n"] == 2 and pct["p99_ms"] == 3.0
        assert led.summary()["total"] == {"ok": 3, "failed": 0}

    def test_failed_results_counted(self):
        led = BarrierLedger()
        led.begin(1, False, 0.0)
        led.finish(1, 5.0, "failed")
        assert led.summary()["total"]["failed"] == 1
        assert led.history()[0]["result"] == "failed"

    def test_malformed_events_ignored(self):
        led = BarrierLedger()
        led.begin(1, False, 0.0)
        led.ingest_events([{"nope": 1}, None, {"epoch": 1,
                           "stage": "collect", "ms": "x"}])
        led.ingest_events([{"epoch": 1, "stage": "collect", "ms": 1.0}])
        assert led.get(1)["stages"] == {"collect": 1.0}


# -- single-process waterfall + reconciliation --------------------------------


def _ticked_session(data_dir=None, **kw):
    s = Session(source_chunk_capacity=CAP, checkpoint_frequency=2,
                data_dir=data_dir, **kw)
    s.run_sql(BID_DDL)
    s.run_sql(AGG)
    for _ in range(6):
        s.tick()
    s.flush()
    return s


class TestSingleProcessWaterfall:
    def test_every_epoch_has_a_record_with_conductor_stages(self, tmp_path):
        s = _ticked_session(data_dir=str(tmp_path))
        try:
            hist = s._barrier_ledger.history()
            assert len(hist) >= 6
            for rec in hist:
                assert set(CONDUCTOR_STAGES) - {"commit"} \
                    <= set(rec["stages"])
                assert rec["result"] == "ok"
                assert rec["total_ms"] is not None
            # checkpoint epochs commit durable state: commit +
            # storage_commit appear on exactly those records (the commit
            # may land from the async flush thread — drain it in)
            from risingwave_tpu.common.barrier_ledger import GLOBAL_STAGES
            s._barrier_ledger.ingest_events(GLOBAL_STAGES.drain())
            ckpt = [r for r in hist if r["checkpoint"]]
            assert ckpt
            for rec in ckpt:
                assert "commit" in rec["stages"]
                assert "storage_commit" in rec["stages"]
        finally:
            s.close()

    def test_stage_sum_reconciles_with_barrier_latency(self):
        """The ISSUE acceptance: waterfall stage sums reconcile with the
        existing p50/p99 barrier latency metrics — per record, the
        conductor stages account for the measured total (inject is
        outside the latency clock), and the ledger's totals line up with
        the latency recorder's percentiles."""
        s = _ticked_session()
        try:
            hist = s._barrier_ledger.history()
            for rec in hist:
                ssum = sum(rec["stages"].get(st, 0.0)
                           for st in CONDUCTOR_STAGES)
                assert ssum <= rec["total_ms"] + 1.0
                assert ssum >= 0.8 * rec["total_ms"] - 1.0, \
                    (rec["epoch"], ssum, rec["total_ms"])
            lat = s.metrics()["barrier_latency"]
            totals = sorted(r["total_ms"] for r in hist)
            # same sample population → the recorder's percentiles fall
            # inside the ledger's observed range
            assert totals[0] - 0.5 <= lat["p50_ms"] <= totals[-1] + 0.5
            assert totals[0] - 0.5 <= lat["p99_ms"] <= totals[-1] + 0.5
        finally:
            s.close()

    def test_sink_deliver_stage_recorded(self, tmp_path):
        s = Session(data_dir=str(tmp_path), checkpoint_frequency=2)
        try:
            s.run_sql("CREATE TABLE t (a INT)")
            out = tmp_path / "out.jsonl"
            s.run_sql(f"CREATE SINK snk FROM t WITH ("
                      f"connector='file', path='{out}', format='jsonl')")
            s.run_sql("INSERT INTO t VALUES (1), (2)")
            s.run_sql("FLUSH")
            stages = set()
            for rec in s._barrier_ledger.history():
                stages |= set(rec["stages"])
            assert "sink_deliver" in stages
        finally:
            s.close()

    def test_zero_added_dispatches_depth_1_and_2(self):
        """The observatory is host-side bookkeeping only: the fused
        one-dispatch-per-epoch invariant holds untouched at pipeline
        depth 1 AND 2 (ISSUE 16 acceptance)."""
        from risingwave_tpu.common.dispatch_count import count_dispatches
        from risingwave_tpu.frontend.build import BuildConfig
        qn = "build_group_epoch.<locals>.coscheduled_epoch"

        def run(depth):
            with count_dispatches() as c:
                s = Session(config=BuildConfig(coschedule=True),
                            source_chunk_capacity=CAP,
                            pipeline_depth=depth,
                            checkpoint_frequency=2)
                try:
                    s.run_sql(BID_DDL)
                    s.run_sql(AGG)
                    for _ in range(5):
                        s.tick()
                    s.flush()
                    n_records = len(s._barrier_ledger.history())
                finally:
                    s.close()
                return dict(c.counts), n_records

        c1, n1 = run(1)
        c2, n2 = run(2)
        assert n1 >= 5 and n2 >= 5       # the ledger observed the run
        assert c1.get(qn) == c2.get(qn) and c1.get(qn), (c1, c2)

    def test_chrome_trace_exports_barrier_flow_events(self):
        s = _ticked_session()
        try:
            obj = s.export_chrome_trace()
            flows = [ev for ev in obj["traceEvents"]
                     if ev.get("ph") in ("s", "t", "f")]
            assert flows, "no barrier flow events in the trace"
            starts = [ev for ev in flows if ev["ph"] == "s"]
            finishes = [ev for ev in flows if ev["ph"] == "f"]
            assert {ev["id"] for ev in starts} \
                == {ev["id"] for ev in finishes}
            assert all(ev["cat"] == "epoch" for ev in flows)
        finally:
            s.close()


# -- config knobs (satellite: capture ring size + history capacity) -----------


class TestObservabilityKnobs:
    def test_knobs_load_from_toml_and_size_the_rings(self, tmp_path):
        from risingwave_tpu.common.config import load_config
        p = tmp_path / "rw.toml"
        p.write_text("""
[observability]
barrier_history_capacity = 7
slow_epoch_capture_capacity = 3
""")
        cfg = load_config(str(p))
        assert cfg.observability.barrier_history_capacity == 7
        assert cfg.observability.slow_epoch_capture_capacity == 3
        s = Session(rw_config=cfg)
        try:
            assert s._barrier_ledger.capacity == 7
            assert s._slow_epochs.maxlen == 3
        finally:
            s.close()

    def test_defaults_keep_legacy_sizes(self):
        s = Session()
        try:
            assert s._barrier_ledger.capacity == 256
            assert s._slow_epochs.maxlen == 16
        finally:
            s.close()

    def test_slow_epoch_capture_attaches_waterfall(self):
        s = Session(source_chunk_capacity=CAP, checkpoint_frequency=2)
        try:
            s.run_sql(BID_DDL)
            s.run_sql(AGG)
            s.run_sql("SET slow_epoch_threshold_ms = 0.0001")
            s.tick()
            s.tick()
            slow = s.slow_epochs()
            assert slow
            for cap in slow:
                rec = cap["barrier"]
                assert rec["epoch"] == cap["epoch"]
                assert rec["stages"], rec
            # metrics() strips the heavy span dump but keeps the record
            mslow = s.metrics()["slow_epochs"]
            assert all("spans" not in se and "barrier" in se
                       for se in mslow)
        finally:
            s.close()


# -- SQL catalog + serving-cache exclusion ------------------------------------


class TestTelemetryCatalog:
    def test_history_relation_matches_ledger(self):
        s = _ticked_session()
        try:
            rows = s.run_sql(
                "SELECT epoch, checkpoint, result, total_ms "
                "FROM rw_catalog.rw_barrier_history")
            hist = s._barrier_ledger.history()
            assert [(r["epoch"], r["checkpoint"], r["result"])
                    for r in hist] == [(e, c, res)
                                       for e, c, res, _ in rows]
            # stage columns surface in waterfall order
            cols = [c for c, _ in s.last_select_schema]
            rows2 = s.run_sql("SELECT * FROM rw_barrier_history")
            cols2 = [c for c, _ in s.last_select_schema]
            assert [f"{st}_ms" for st in ALL_STAGES] == cols2[5:-1]
            assert len(rows2) == len(hist)
        finally:
            s.close()

    def test_estate_relations_answer(self):
        s = _ticked_session()
        try:
            assert s.run_sql(
                "SELECT * FROM rw_catalog.rw_barrier_inflight") == []
            frags = s.run_sql("SELECT * FROM rw_fragments")
            assert any(r[0] == "q" for r in frags)
            assert s.run_sql("SELECT * FROM rw_worker_nodes") == []
            prof = s.run_sql(
                "SELECT worker, qualname, calls "
                "FROM rw_dispatch_profiles WHERE calls > 0")
            assert prof and all(r[0] == -1 for r in prof)
            hbm = s.run_sql("SELECT job, state_bytes FROM rw_hbm_ledger")
            assert any(r[0] == "q" and r[1] > 0 for r in hbm)
            assert s.run_sql(
                "SELECT * FROM rw_autoscaler_decisions") == []
        finally:
            s.close()

    def test_describe_path_plans_without_session(self):
        """The session-less Planner (DESCRIBE, recovery replay) must
        still resolve the telemetry relations: schema, zero rows."""
        from risingwave_tpu.frontend.system_catalog import system_relation
        s = Session()
        try:
            for name in ("rw_barrier_history", "rw_barrier_inflight",
                         "rw_actors", "rw_hbm_ledger"):
                schema, rows = system_relation(s.catalog, name)
                assert len(schema) > 0 and rows == []
        finally:
            s.close()

    def test_system_relations_never_touch_serving_cache(self):
        """Satellite: a rw_catalog query must neither populate nor hit
        the plan cache — repeated reads are always fresh plans."""
        s = _ticked_session()
        try:
            stats0 = s.metrics()["serving"]
            for _ in range(3):
                s.run_sql("SELECT * FROM rw_catalog.rw_barrier_history")
                s.run_sql("SELECT * FROM rw_relations")
            stats1 = s.metrics()["serving"]
            assert s._serving.cache_len() == 0
            assert stats1["cache_hits"] == stats0["cache_hits"]
            assert stats1["cache_misses"] == stats0["cache_misses"]
            assert stats1["system_catalog_reads"] \
                >= stats0["system_catalog_reads"] + 6
            # sanity: user queries still cache (the bypass is scoped to
            # system relations, not the plane)
            s.run_sql("SELECT auction, n FROM q")
            s.run_sql("SELECT auction, n FROM q")
            stats2 = s.metrics()["serving"]
            assert s._serving.cache_len() == 1
            assert stats2["cache_hits"] >= 1
            # freshness is the point of the exclusion: new barriers are
            # visible to the very next history read
            before = len(s.run_sql(
                "SELECT epoch FROM rw_catalog.rw_barrier_history"))
            s.tick()
            after = len(s.run_sql(
                "SELECT epoch FROM rw_catalog.rw_barrier_history"))
            assert after == before + 1
        finally:
            s.close()

    def test_subquery_and_join_references_also_bypass(self):
        s = Session()
        try:
            s.run_sql("SELECT * FROM (SELECT name FROM rw_relations) r")
            s.run_sql("SELECT r.name FROM rw_relations r "
                      "JOIN rw_relations r2 ON r.name = r2.name")
            assert s._serving.cache_len() == 0
            assert s.metrics()["serving"]["system_catalog_reads"] >= 2
        finally:
            s.close()


# -- prometheus + ctl surfaces ------------------------------------------------


class TestSurfaces:
    def test_metrics_and_prometheus_families(self):
        from risingwave_tpu.frontend.prometheus import render_metrics
        s = _ticked_session()
        try:
            b = s.metrics()["barrier"]
            assert b["inflight"] == 0 and b["total"]["ok"] >= 6
            assert "collect" in b["stages"]
            text = render_metrics(s)
            assert 'rw_barrier_stage_seconds{stage="collect",' \
                   'quantile="0.5"}' in text
            assert "rw_barrier_inflight 0" in text
            assert 'rw_barrier_total{result="ok"}' in text
            assert 'rw_barrier_total{result="failed"} 0' in text
        finally:
            s.close()

    def test_ctl_trace_barrier_over_live_session(self, capsys):
        from risingwave_tpu.cli import _ctl_dispatch
        s = _ticked_session()
        try:
            args = types.SimpleNamespace(what="trace", sub="barrier",
                                         json=False, inflight=False)
            _ctl_dispatch(args, s, json)
            out = capsys.readouterr().out
            assert "epoch\tckpt\tresult\ttotal_ms" in out
            assert "collect\t" in out            # percentile table
            args.json = True
            _ctl_dispatch(args, s, json)
            obj = json.loads(capsys.readouterr().out)
            assert len(obj["history"]) >= 6
            assert "collect" in obj["stages"]
            args.json, args.inflight = False, True
            _ctl_dispatch(args, s, json)
            assert "no in-flight barriers" in capsys.readouterr().out
        finally:
            s.close()


# -- 2-worker federation + chaos blame (the acceptance runs) ------------------


def _spanning_session(data_dir, **kw):
    from risingwave_tpu.frontend.build import BuildConfig
    return Session(workers=2, seed=42, data_dir=data_dir,
                   source_chunk_capacity=CAP,
                   config=BuildConfig(fragment_parallelism=2), **kw)


@pytest.mark.slow
class TestFederatedWaterfall:
    def test_spanning_record_matches_single_process_stage_for_stage(self):
        """A 2-worker spanning job's federated waterfall carries every
        conductor stage the single-process record has — stage for
        stage — plus both workers' collect/storage detail."""
        sp = _spanning_session(tempfile.mkdtemp(),
                               checkpoint_frequency=2)
        try:
            sp.run_sql(BID_DDL)
            sp.run_sql(AGG)
            for _ in range(6):
                sp.tick()
            sp.flush()
            sp._federate_worker_stats(force=True)
            span_hist = {r["epoch"]: r
                         for r in sp._barrier_ledger.history()}
        finally:
            sp.close()
        lo = _ticked_session()
        try:
            local_hist = {r["epoch"]: r
                          for r in lo._barrier_ledger.history()}
        finally:
            lo.close()
        shared = sorted(set(span_hist) & set(local_hist))
        assert len(shared) >= 4
        for e in shared:
            sp_rec, lo_rec = span_hist[e], local_hist[e]
            assert sp_rec["checkpoint"] == lo_rec["checkpoint"]
            # conductor stages agree stage-for-stage
            for st in CONDUCTOR_STAGES:
                assert (st in sp_rec["stages"]) \
                    == (st in lo_rec["stages"]), (e, st)
        # worker-side stages federated in: both workers contributed
        # barrier collection, and checkpoint epochs their 2PC prepare
        wids = set()
        stages_by_wid: dict = {}
        for rec in span_hist.values():
            for wid, st in rec["workers"].items():
                if wid >= 0:
                    wids.add(wid)
                    stages_by_wid.setdefault(wid, set()).update(st)
        assert wids == {0, 1}, wids
        for wid in (0, 1):
            assert "worker_collect" in stages_by_wid[wid]
            assert "storage_prepare" in stages_by_wid[wid]

    def test_worker_and_placement_relations_over_spanning_job(self):
        s = _spanning_session(tempfile.mkdtemp())
        try:
            s.run_sql(BID_DDL)
            s.run_sql(AGG)
            for _ in range(2):
                s.tick()
            nodes = s.run_sql(
                "SELECT worker_id, dead FROM rw_worker_nodes")
            assert [(0, False), (1, False)] == sorted(nodes)
            actors = s.run_sql(
                "SELECT job, fragment_id, actor_id, worker "
                "FROM rw_actors WHERE job = 'q'")
            assert len(actors) >= 2
            assert {r[3] for r in actors} == {0, 1}
            placements = s.run_sql("SELECT job, workers "
                                   "FROM rw_placements")
            assert ("q", "0,1") in placements
        finally:
            s.close()


@pytest.mark.slow
class TestStuckBarrierBlame:
    def test_partitioned_edge_blamed_by_name_before_deadline(self):
        """THE acceptance run: one exchange edge of a spanning 2-worker
        job partitioned by a seeded ChaosSchedule; the in-flight barrier
        is diagnosed by name — consumer actor + link — through
        ``barrier_blame()``, ``ctl trace barrier --inflight`` and
        ``SELECT * FROM rw_catalog.rw_barrier_inflight`` over pgwire,
        all while the epoch deadline has NOT fired."""
        from risingwave_tpu.cli import _ctl_dispatch
        from risingwave_tpu.common.config import FaultConfig
        from risingwave_tpu.rpc.faults import (
            CHAOS_ENV, ChaosRule, ChaosSchedule, install,
        )
        # partition barrier frames on the w0->w1 exchange edge from
        # epoch 8 on; epochs before that warm the graph up cleanly
        stuck_from = 8
        schedule = ChaosSchedule(11, [ChaosRule(
            kind="partition", link="w0->w1",
            types=["exg_data:barrier"], epochs=[stuck_from, 10_000])])
        os.environ[CHAOS_ENV] = schedule.to_json()
        install(schedule)
        s = None
        try:
            s = _spanning_session(
                tempfile.mkdtemp(),
                fault_config=FaultConfig(worker_epoch_timeout_s=60.0))
            s.run_sql(BID_DDL)
            s.run_sql(AGG)
            while s.epoch < stuck_from - 1:
                s.tick()
            s.run_sql("SET in_flight_barrier_nums = 2")
            # this tick injects the first partitioned epoch; with the
            # pipelined window open it returns WITHOUT collecting
            s.tick()
            assert s._inflight, "barrier unexpectedly completed"
            stuck_epoch = s._inflight[0][0]
            assert stuck_epoch >= stuck_from
            # (1) the API names the starved edge's consumer actor
            findings = s.barrier_blame()
            assert findings
            assert not s._dead_jobs          # deadline has NOT fired
            edge = [f for f in findings if f["kind"] == "exchange_edge"
                    and f["link"] == "w0->w1"]
            assert edge, findings
            f = edge[0]
            assert f["epoch"] == stuck_epoch and f["job"] == "q"
            assert f["worker"] == 1          # the starved consumer side
            assert f["actor"] is not None and f["fragment"] is not None
            assert f["edge"].startswith("q:f")
            # the named consumer actor really lives on worker 1
            placed = {(r[1], r[2]): r[3] for r in s.run_sql(
                "SELECT job, fragment_id, actor_id, worker "
                "FROM rw_actors WHERE job = 'q'")}
            assert placed[(f["fragment"], f["actor"])] == 1
            # the un-acking worker is named too
            assert any(ff["kind"] == "worker" and ff["worker"] == 1
                       for ff in findings), findings
            # (2) ctl trace barrier --inflight over the live session
            import io
            from contextlib import redirect_stdout
            buf = io.StringIO()
            args = types.SimpleNamespace(what="trace", sub="barrier",
                                         json=False, inflight=True)
            with redirect_stdout(buf):
                _ctl_dispatch(args, s, json)
            out = buf.getvalue()
            assert "exchange_edge" in out and "w0->w1" in out
            assert f"f{f['fragment']}a{f['actor']}" in out
            # (3) the same diagnosis over pgwire
            cols, rows = _pgwire_select(
                s, "SELECT epoch, kind, job, worker, actor, link "
                   "FROM rw_catalog.rw_barrier_inflight")
            assert "link" in cols
            hits = [r for r in rows if r[1] == "exchange_edge"
                    and r[5] == "w0->w1"]
            assert hits, rows
            assert hits[0][0] == str(stuck_epoch)
            assert hits[0][4] == str(f["actor"])
            assert not s._dead_jobs          # still before the deadline
        finally:
            os.environ.pop(CHAOS_ENV, None)
            install(None)
            if s is not None:
                # the stuck epoch can only resolve through the deadline
                # path; shorten it so teardown doesn't ride out 60 s
                for w in s.workers:
                    w.epoch_timeout = 1.0
                try:
                    s.close()
                except Exception:
                    pass


def _pgwire_select(session, sql):
    """Run one SELECT over a real pgwire connection against the live
    session; returns (columns, text rows)."""
    import struct

    from risingwave_tpu.frontend.pgwire import PgWireServer

    async def go():
        server = PgWireServer(session, "127.0.0.1", 0)
        await server.start()
        port = server._server.sockets[0].getsockname()[1]
        try:
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", port)
            params = b"user\x00test\x00database\x00dev\x00\x00"
            body = struct.pack("!I", 196608) + params
            writer.write(struct.pack("!I", len(body) + 4) + body)
            await writer.drain()

            async def read_msg():
                hdr = await reader.readexactly(5)
                ln = struct.unpack("!I", hdr[1:5])[0]
                return hdr[0:1], await reader.readexactly(ln - 4)

            while True:
                tag, _ = await read_msg()
                if tag == b"Z":
                    break
            q = sql.encode() + b"\x00"
            writer.write(b"Q" + struct.pack("!I", len(q) + 4) + q)
            await writer.drain()
            cols, rows = [], []
            while True:
                tag, payload = await read_msg()
                if tag == b"T":
                    n = struct.unpack("!H", payload[:2])[0]
                    off = 2
                    for _ in range(n):
                        end = payload.index(b"\x00", off)
                        cols.append(payload[off:end].decode())
                        off = end + 1 + 18
                elif tag == b"D":
                    n = struct.unpack("!H", payload[:2])[0]
                    off = 2
                    row = []
                    for _ in range(n):
                        ln = struct.unpack("!i",
                                           payload[off:off + 4])[0]
                        off += 4
                        if ln == -1:
                            row.append(None)
                        else:
                            row.append(payload[off:off + ln].decode())
                            off += ln
                    rows.append(tuple(row))
                elif tag == b"E":
                    raise AssertionError(payload)
                elif tag == b"Z":
                    break
            writer.write(b"X" + struct.pack("!I", 4))
            writer.close()
            return cols, rows
        finally:
            await server.close()

    return asyncio.new_event_loop().run_until_complete(go())
