"""SSTable unit tests: build/read roundtrip, block index, bloom filter,
tombstones, ordering enforcement, merge semantics, corruption detection."""

import pytest

from risingwave_tpu.storage.object_store import MemObjectStore
from risingwave_tpu.storage.sstable import (
    CorruptSst, Sstable, SstBuilder, build_sst, load_sst, merge_iter,
)


def _sst(entries, block=64):
    return Sstable(build_sst(entries, block_target_bytes=block))


class TestSstable:
    def test_roundtrip_multiblock(self):
        entries = [(1, b"k%04d" % i, b"v%d" % i) for i in range(500)]
        s = _sst(entries, block=128)          # many blocks
        assert s.n_entries == 500
        assert len(s.meta["index"]) > 5
        assert list(s.iter_entries()) == entries
        for i in (0, 1, 250, 498, 499):
            assert s.lookup(1, b"k%04d" % i) == (True, b"v%d" % i)
        assert s.lookup(1, b"k9999") == (False, None)
        assert s.lookup(2, b"k0001") == (False, None)

    def test_multi_table_composite_order(self):
        entries = ([(1, b"z", b"a")] + [(2, b"a", b"b")]
                   + [(7, b"m", b"c")])
        s = _sst(entries)
        assert s.table_ids == [1, 2, 7]
        assert s.key_range() == ((1, b"z"), (7, b"m"))
        assert s.lookup(2, b"a") == (True, b"b")

    def test_tombstone_found_and_distinct_from_missing(self):
        s = _sst([(1, b"dead", None), (1, b"live", b"v")])
        assert s.lookup(1, b"dead") == (True, None)    # tombstone
        assert s.lookup(1, b"gone") == (False, None)   # absent
        assert s.meta["n_tombstones"] == 1

    def test_out_of_order_rejected(self):
        b = SstBuilder()
        b.add(1, b"b", b"x")
        with pytest.raises(ValueError, match="strictly increasing"):
            b.add(1, b"a", b"y")
        with pytest.raises(ValueError, match="strictly increasing"):
            b.add(1, b"b", b"y")               # duplicate

    def test_empty_sst(self):
        s = _sst([])
        assert s.n_entries == 0
        assert s.key_range() is None
        assert s.lookup(1, b"k") == (False, None)
        assert list(s.iter_entries()) == []

    def test_bloom_negative_short_circuit(self):
        s = _sst([(1, b"k%03d" % i, b"v") for i in range(100)])
        # absent keys overwhelmingly answer without a block scan
        misses = sum(s.may_contain(1, b"absent%d" % i) for i in range(500))
        assert misses < 50                     # ~1% fp at 10 bits/key

    def test_corruption_detected(self):
        data = build_sst([(1, b"k", b"v")])
        with pytest.raises(CorruptSst):
            Sstable(data[:-4])                 # truncated footer
        with pytest.raises(CorruptSst):
            Sstable(data[:8])                  # hopeless
        bad = data[:-8] + b"NOTMAGIC"
        with pytest.raises(CorruptSst):
            Sstable(bad)

    def test_load_via_object_store(self):
        os_ = MemObjectStore()
        os_.put("x.sst", build_sst([(3, b"a", b"1")]))
        s = load_sst(os_, "x.sst")
        assert s.lookup(3, b"a") == (True, b"1")
        with pytest.raises(FileNotFoundError):
            load_sst(os_, "missing.sst")


class TestMergeIter:
    def test_newest_wins_and_tombstones_pass(self):
        newest = _sst([(1, b"a", b"NEW"), (1, b"b", None)])
        oldest = _sst([(1, b"a", b"OLD"), (1, b"b", b"OLD"),
                       (1, b"c", b"keep")])
        merged = list(merge_iter([newest, oldest]))
        assert merged == [(1, b"a", b"NEW"), (1, b"b", None),
                          (1, b"c", b"keep")]

    def test_three_way_merge_order(self):
        r0 = _sst([(1, b"b", b"r0")])
        r1 = _sst([(1, b"a", b"r1"), (1, b"b", b"r1")])
        r2 = _sst([(1, b"c", b"r2"), (2, b"a", b"r2")])
        merged = list(merge_iter([r0, r1, r2]))
        assert merged == [(1, b"a", b"r1"), (1, b"b", b"r0"),
                          (1, b"c", b"r2"), (2, b"a", b"r2")]
