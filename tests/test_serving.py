"""High-QPS serving plane (ROADMAP item 3): two-phase distributed batch
aggregation + the version-pinned plan cache + the concurrent read path.

What these tests pin:
  * ``vnode_partitions`` edge cases — clamping and exactly-once coverage
    (the slice algebra everything two-phase relies on);
  * ``BatchTaskManager.collect`` keeps a timed-out task collectable (the
    old pop-before-wait leaked the future forever) and ``shutdown()``
    stops the pool;
  * two-phase partial/merge aggregation is BIT-EXACT vs the single-phase
    executor under a randomized workload — multi-column group keys,
    count/sum/min/max, avg-as-sum+count, string MIN/MAX, NULLs — for any
    slicing of the vnode space;
  * a repeated identical SELECT creates ZERO new jit wrappers
    (common/dispatch_count.py), a write in between re-executes the SAME
    cached executors (still zero) and returns the new snapshot;
  * the cache is LRU-bounded by ``[batch] serving_cache_size`` and DDL
    clears it;
  * concurrent readers drive ``Session.query`` from many threads while
    the stream keeps ticking — no torn reads, ticks not blocked;
  * serving counters federate into ``Session.metrics()["serving"]`` and
    the Prometheus exposition.

Reference: the partial/final agg split + frontend query caches,
src/frontend/src/scheduler/distributed/query.rs:69-115.
"""

import concurrent.futures
import random
import threading
import time

import pytest

from risingwave_tpu.batch import (
    BatchHashAgg, BatchMergeAgg, BatchPartialAgg, BatchTaskManager,
    RowSeqScan, run_batch, vnode_partitions,
)
from risingwave_tpu.batch.executors import BatchRows, partial_agg_fields
from risingwave_tpu.common.hashing import VNODE_COUNT
from risingwave_tpu.common.types import (
    FLOAT64, INT64, VARCHAR, Field, Schema,
)
from risingwave_tpu.expr.agg import agg, count_star
from risingwave_tpu.storage.state_store import MemoryStateStore
from risingwave_tpu.storage.state_table import StateTable


class TestVnodePartitions:
    def test_more_tasks_than_vnodes_clamps(self):
        parts = vnode_partitions(VNODE_COUNT + 100)
        assert len(parts) == VNODE_COUNT
        assert all(len(p) == 1 for p in parts)
        assert sorted(v for p in parts for v in p) == list(range(VNODE_COUNT))

    def test_zero_and_negative_clamp_to_one(self):
        for n in (0, -1, -100):
            parts = vnode_partitions(n)
            assert len(parts) == 1
            assert parts[0] == list(range(VNODE_COUNT))

    def test_remainder_distribution_covers_exactly_once(self):
        for n in (1, 3, 5, 7, 100, 255, 256):
            parts = vnode_partitions(n)
            assert len(parts) == n
            flat = [v for p in parts for v in p]
            assert sorted(flat) == list(range(VNODE_COUNT))
            assert len(set(flat)) == VNODE_COUNT
            # contiguous slices in order
            assert flat == list(range(VNODE_COUNT))
            # balanced: sizes differ by at most one
            sizes = {len(p) for p in parts}
            assert max(sizes) - min(sizes) <= 1


class TestTaskManagerLeak:
    def test_timed_out_task_stays_collectable(self):
        mgr = BatchTaskManager(max_workers=1)
        ev = threading.Event()

        class _Slow:
            def execute(self):
                ev.wait(5.0)
                yield [(1,)]

        tid = mgr.fire_task(lambda _vn: _Slow())
        with pytest.raises(concurrent.futures.TimeoutError):
            mgr.collect(tid, timeout=0.05)
        assert mgr.pending() == 1          # future NOT leaked
        ev.set()
        assert mgr.collect(tid, timeout=5.0) == [(1,)]
        assert mgr.pending() == 0
        with pytest.raises(KeyError):
            mgr.collect(tid)               # retrieved exactly once
        mgr.shutdown()

    def test_shutdown_stops_pool(self):
        mgr = BatchTaskManager(max_workers=1)

        class _One:
            def execute(self):
                yield [(7,)]

        tid = mgr.fire_task(lambda _vn: _One())
        assert mgr.collect(tid) == [(7,)]
        mgr.shutdown()
        assert mgr.pending() == 0


SCHEMA = Schema((Field("k", INT64), Field("g1", INT64), Field("g2", INT64),
                 Field("v", INT64), Field("f", FLOAT64),
                 Field("s", VARCHAR)))


def _random_table(seed: int, n: int = 400):
    rng = random.Random(seed)
    store = MemoryStateStore()
    t = StateTable(store, 1, SCHEMA, [0])
    words = ["apple", "pear", "zebra", "kiwi", "mango", "fig"]
    for i in range(n):
        v = rng.randrange(-50, 100) if rng.random() > 0.1 else None
        # dyadic floats (x/8, bounded): f64 addition is exact in ANY
        # order, so the float-sum lanes stay bit-identical across phases
        f = rng.randrange(-800, 800) / 8.0 if rng.random() > 0.1 else None
        s = rng.choice(words) if rng.random() > 0.15 else None
        row = (i, rng.randrange(6), rng.randrange(3), v, f, s)
        t.insert(tuple(
            None if x is None else SCHEMA[j].type.to_physical(x)
            for j, x in enumerate(row)))
    t.commit(1)
    store.commit(1)
    return t


CALLS = [count_star(), agg("sum", 3, INT64), agg("min", 3, INT64),
         agg("max", 3, INT64), agg("avg", 3, INT64),
         agg("max", 4, FLOAT64), agg("sum", 4, FLOAT64),
         agg("min", 5, VARCHAR), agg("max", 5, VARCHAR)]


class TestTwoPhaseParity:
    @pytest.mark.parametrize("seed,n_tasks", [(7, 4), (11, 1), (13, 7)])
    def test_randomized_bit_exact(self, seed, n_tasks):
        t = _random_table(seed)
        gk = [1, 2]
        single = sorted(run_batch(BatchHashAgg(RowSeqScan(t), gk, CALLS)))
        partial_rows = []
        for part in vnode_partitions(n_tasks):
            partial_rows.extend(run_batch(
                BatchPartialAgg(RowSeqScan(t, vnodes=part), gk, CALLS)))
        pschema = Schema(partial_agg_fields(SCHEMA, gk, CALLS))
        merged = sorted(run_batch(BatchMergeAgg(
            BatchRows(pschema, lambda: partial_rows),
            tuple(SCHEMA[i].type for i in gk), CALLS)))
        assert single == merged

    @pytest.mark.slow
    def test_one_task_per_vnode_bit_exact(self):
        """The degenerate maximal split: 256 tasks, one vnode each (56 s
        of per-task jit instances — CI runs it in the check.sh serving
        subset, tier-1 skips it)."""
        t = _random_table(17)
        gk = [1, 2]
        single = sorted(run_batch(BatchHashAgg(RowSeqScan(t), gk, CALLS)))
        partial_rows = []
        for part in vnode_partitions(256):
            partial_rows.extend(run_batch(
                BatchPartialAgg(RowSeqScan(t, vnodes=part), gk, CALLS)))
        pschema = Schema(partial_agg_fields(SCHEMA, gk, CALLS))
        merged = sorted(run_batch(BatchMergeAgg(
            BatchRows(pschema, lambda: partial_rows),
            tuple(SCHEMA[i].type for i in gk), CALLS)))
        assert single == merged

    def test_single_column_key_and_empty_slices(self):
        t = _random_table(23, n=40)      # few rows: many empty slices
        gk = [1]
        single = sorted(run_batch(BatchHashAgg(RowSeqScan(t), gk, CALLS)))
        partial_rows = []
        for part in vnode_partitions(16):
            partial_rows.extend(run_batch(
                BatchPartialAgg(RowSeqScan(t, vnodes=part), gk, CALLS)))
        pschema = Schema(partial_agg_fields(SCHEMA, gk, CALLS))
        merged = sorted(run_batch(BatchMergeAgg(
            BatchRows(pschema, lambda: partial_rows),
            (SCHEMA[1].type,), CALLS)))
        assert single == merged

    def test_empty_table_merges_to_nothing(self):
        store = MemoryStateStore()
        t = StateTable(store, 1, SCHEMA, [0])
        gk = [1]
        partial_rows = []
        for part in vnode_partitions(4):
            partial_rows.extend(run_batch(
                BatchPartialAgg(RowSeqScan(t, vnodes=part), gk, CALLS)))
        assert partial_rows == []
        pschema = Schema(partial_agg_fields(SCHEMA, gk, CALLS))
        merged = run_batch(BatchMergeAgg(
            BatchRows(pschema, lambda: partial_rows),
            (SCHEMA[1].type,), CALLS))
        assert merged == []


def _session(**batch_overrides):
    from risingwave_tpu.common.config import load_config
    from risingwave_tpu.frontend import Session
    overrides = {f"batch.{k}": v for k, v in batch_overrides.items()}
    return Session(rw_config=load_config(None, **overrides))


class TestServingCache:
    def test_repeat_select_zero_new_jits_and_write_invalidation(self):
        from risingwave_tpu.common.dispatch_count import count_dispatches
        s = _session()
        try:
            s.run_sql("CREATE TABLE t (a BIGINT, b BIGINT)")
            s.run_sql("INSERT INTO t VALUES (1,10),(2,20),(1,30)")
            s.flush()
            sql = "SELECT a, count(*), sum(b) FROM t GROUP BY a"
            first = s.run_sql(sql)       # warm: plan + lower + jit
            with count_dispatches() as c:
                assert s.run_sql(sql) == first
                assert c.total == 0, dict(c.counts)
                s.run_sql("INSERT INTO t VALUES (2, 5)")
                s.flush()
                rows = s.run_sql(sql)
                assert c.total == 0, dict(c.counts)
            assert sorted(rows) == [(1, 2, 40), (2, 2, 25)]
            m = s.metrics()["serving"]
            assert m["cache_hits"] >= 1
            assert m["cache_misses"] >= 1
            assert m["reexecutions"] >= 1
            assert m["two_phase_queries"] >= 1
            assert m["tasks_fired_local"] >= 1
            assert m["partials_merged"] >= 1
        finally:
            s.close()

    def test_lru_bound_from_rw_config(self):
        s = _session(serving_cache_size=2)
        try:
            s.run_sql("CREATE TABLE t (a BIGINT)")
            s.run_sql("INSERT INTO t VALUES (1),(2),(3)")
            s.flush()
            for i in range(5):
                s.run_sql(f"SELECT a FROM t WHERE a > {i}")
            assert s._serving.cache_len() <= 2
            m = s.metrics()["serving"]
            assert m["cache_size"] <= 2
            assert m["cache_misses"] >= 5
        finally:
            s.close()

    def test_cache_disabled_still_correct(self):
        s = _session(serving_cache_size=0, serving_tasks=1)
        try:
            s.run_sql("CREATE TABLE t (a BIGINT, b BIGINT)")
            s.run_sql("INSERT INTO t VALUES (1,10),(2,20)")
            s.flush()
            sql = "SELECT a, sum(b) FROM t GROUP BY a"
            assert sorted(s.run_sql(sql)) == [(1, 10), (2, 20)]
            assert sorted(s.run_sql(sql)) == [(1, 10), (2, 20)]
            assert s._serving.cache_len() == 0
        finally:
            s.close()

    def test_ddl_clears_cache(self):
        s = _session()
        try:
            s.run_sql("CREATE TABLE t (a BIGINT)")
            s.run_sql("INSERT INTO t VALUES (1),(2)")
            s.flush()
            s.run_sql("SELECT a FROM t WHERE a > 0")
            assert s._serving.cache_len() == 1
            s.run_sql("CREATE TABLE u (b BIGINT)")
            assert s._serving.cache_len() == 0
            m = s.metrics()["serving"]
            assert m["catalog_invalidations"] >= 1
            # and the statement still answers correctly after the clear
            assert sorted(s.run_sql("SELECT a FROM t WHERE a > 0")) == \
                [(1,), (2,)]
        finally:
            s.close()

    def test_order_by_and_having_tail_served_from_cache(self):
        s = _session()
        try:
            s.run_sql("CREATE TABLE t (a BIGINT, b BIGINT)")
            s.run_sql("INSERT INTO t VALUES (1,10),(1,20),(2,5),(3,40)")
            s.flush()
            sql = ("SELECT a, sum(b) AS sb FROM t GROUP BY a "
                   "HAVING sum(b) > 6 ORDER BY a DESC")
            expect = [(3, 40), (1, 30)]
            assert s.run_sql(sql) == expect
            assert s.run_sql(sql) == expect
            assert s.metrics()["serving"]["cache_hits"] >= 1
        finally:
            s.close()

    def test_prometheus_exposes_serving(self):
        from risingwave_tpu.frontend.prometheus import render_metrics
        s = _session()
        try:
            s.run_sql("CREATE TABLE t (a BIGINT)")
            s.run_sql("INSERT INTO t VALUES (1)")
            s.flush()
            s.run_sql("SELECT a, count(*) FROM t GROUP BY a")
            s.run_sql("SELECT a, count(*) FROM t GROUP BY a")
            text = render_metrics(s)
            assert 'rw_serving_stat{stat="cache_hits"}' in text
            assert 'rw_serving_stat{stat="p99_ms"}' in text
        finally:
            s.close()

    def test_stream_only_shapes_still_work_uncached(self):
        s = _session()
        try:
            s.run_sql("CREATE TABLE t (a BIGINT, b BIGINT)")
            s.run_sql("INSERT INTO t VALUES (1,10),(1,20),(2,5)")
            s.flush()
            # DISTINCT agg is lanes-unsupported: the serving plane must
            # hand it to the stream-fold path, repeatedly
            sql = "SELECT a, count(DISTINCT b) FROM t GROUP BY a"
            assert sorted(s.run_sql(sql)) == [(1, 2), (2, 1)]
            assert sorted(s.run_sql(sql)) == [(1, 2), (2, 1)]
        finally:
            s.close()


class TestConcurrentServing:
    def test_readers_do_not_block_ticks_or_tear(self):
        """4 reader threads hammer Session.query while the stream keeps
        ticking: every result must equal the single-phase answer at SOME
        quiescent version (the seqlock contract), ticks complete, and
        nothing deadlocks."""
        from risingwave_tpu.frontend import Session
        from risingwave_tpu.frontend.parser import parse_sql
        s = Session(source_chunk_capacity=64)
        try:
            s.run_sql("""CREATE SOURCE bid (auction BIGINT, bidder BIGINT,
                price BIGINT, channel VARCHAR, url VARCHAR,
                date_time TIMESTAMP, extra VARCHAR)
                WITH (connector = 'nexmark', nexmark_table = 'bid')""")
            s.run_sql("CREATE MATERIALIZED VIEW m AS SELECT auction, "
                      "count(*) AS n FROM bid GROUP BY auction")
            s.tick()
            sel = parse_sql("SELECT auction % 4, sum(n) FROM m "
                            "GROUP BY auction % 4")[0].select
            s.query(sel)                 # warm
            errors: list = []
            results: list = []
            stop = threading.Event()

            def reader():
                try:
                    while not stop.is_set():
                        results.append(sorted(s.query(sel)))
                except BaseException as e:  # noqa: BLE001
                    errors.append(e)

            threads = [threading.Thread(target=reader) for _ in range(4)]
            for t in threads:
                t.start()
            t0 = time.perf_counter()
            for _ in range(8):
                s.tick()
            tick_wall = time.perf_counter() - t0
            stop.set()
            for t in threads:
                t.join(timeout=30)
            assert not errors, errors
            assert all(not t.is_alive() for t in threads)
            assert len(results) > 0
            # ground truth at the final quiescent version
            s.flush()
            final = sorted(s.query(sel))
            assert sorted(s.query(sel)) == final
            # every observed result is internally consistent: group sums
            # are non-negative and the group key space is bounded
            for r in results:
                assert all(0 <= k < 4 for k, _ in r)
                assert all(n >= 0 for _, n in r)
            assert tick_wall < 60
            m = s.metrics()["serving"]
            assert m["p99_ms"] >= 0
            assert m["cache_hits"] > 0
        finally:
            s.close()


class TestFallbackAndSlices:
    def test_cached_entry_batchfallback_falls_back_not_raises(self):
        """A cached plan whose re-execution trips BatchFallback (data
        grew into a shape the cached executors cannot serve) must fall
        back to a fresh build / the stream-fold path — the pre-cache
        guarantee — not surface the exception."""
        from risingwave_tpu.batch.executors import BatchFallback
        s = _session()
        try:
            s.run_sql("CREATE TABLE t (a BIGINT, b BIGINT)")
            s.run_sql("INSERT INTO t VALUES (1,10),(2,20)")
            s.flush()
            sql = "SELECT a, sum(b) FROM t GROUP BY a"
            assert sorted(s.run_sql(sql)) == [(1, 10), (2, 20)]
            # force the cached runner to trip the fallback on its next
            # (version-bumped) re-execution
            (ent,) = s._serving._cache.values()

            def boom():
                raise BatchFallback("forced: shape outgrew the plan")

            ent.runner = boom
            s.run_sql("INSERT INTO t VALUES (1, 5)")
            s.flush()
            assert sorted(s.run_sql(sql)) == [(1, 15), (2, 20)]
            assert s.metrics()["serving"]["fallbacks"] >= 1
        finally:
            s.close()

    def test_single_phase_agg_refuses_vnode_slice(self):
        """lower_plan must refuse a SINGLE-phase agg under a vnode
        restriction (per-slice groups would union into duplicates) while
        the partial phase accepts it."""
        from risingwave_tpu.batch.lower import lower_plan, split_two_phase
        from risingwave_tpu.frontend import planner as P
        t = _random_table(31, n=20)

        class _Def:
            table_id, schema, pk = 1, SCHEMA, (0,)
            name = "t"

        scan = P.PTableScan(schema=SCHEMA, pk=(0,), table=_Def())
        agg = P.PAgg(schema=Schema((SCHEMA[1], Field("n", INT64))),
                     pk=(0,), input=scan, group_keys=(1,),
                     agg_calls=(count_star(),))
        assert lower_plan(agg, t.store, vnodes=[0, 1, 2]) is None
        assert lower_plan(agg, t.store) is not None
        split = split_two_phase(agg)
        assert split is not None
        assert lower_plan(split.partial_plan, t.store,
                          vnodes=[0, 1, 2]) is not None


class TestTaskFailureAndDdlSeqlock:
    def test_failed_task_outcome_pops_entry_and_discard(self):
        mgr = BatchTaskManager(max_workers=1)

        class _Boom:
            def execute(self):
                raise RuntimeError("task died")
                yield  # pragma: no cover

        tid = mgr.fire_task(lambda _vn: _Boom())
        with pytest.raises(RuntimeError):
            mgr.collect(tid)
        assert mgr.pending() == 0        # failure IS retrieval: no leak

        class _Ok:
            def execute(self):
                yield [(1,)]

        t2 = mgr.fire_task(lambda _vn: _Ok())
        mgr.discard(t2)
        assert mgr.pending() == 0
        mgr.shutdown()

    def test_ddl_moves_the_data_version(self):
        """CREATE/DROP rearrange store tables, so they must move the
        seqlock version — a lock-free optimistic reader racing a DROP
        retries instead of accepting a torn scan."""
        s = _session()
        try:
            v0 = s._data_version
            s.run_sql("CREATE TABLE t (a BIGINT)")
            v1 = s._data_version
            assert v1 > v0 and v1 % 2 == 0
            s.run_sql("DROP TABLE t")
            v2 = s._data_version
            assert v2 > v1 and v2 % 2 == 0
        finally:
            s.close()
