"""Durable checkpoint + crash recovery (VERDICT r2 item 3).

The e2e test REALLY kills the process: a subprocess builds a session over a
data dir, checkpoints via FLUSH, then os._exit(0)s without any graceful
shutdown; the parent recovers a fresh Session from the directory and
cross-checks MV contents, then keeps streaming into the recovered session."""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from risingwave_tpu.common.row import decode_value_row, encode_value_row
from risingwave_tpu.common.types import (
    BOOL, FLOAT64, INT64, VARCHAR, GLOBAL_STRING_DICT,
)
from risingwave_tpu.storage.checkpoint import CheckpointLog, DurableStateStore


def test_value_row_roundtrip():
    types = [INT64, FLOAT64, BOOL, VARCHAR, INT64]
    sid = GLOBAL_STRING_DICT.intern("hello world")
    row = (42, -1.5, True, sid, None)
    enc = encode_value_row(row, types)
    assert decode_value_row(enc, types) == row
    # all-null row
    row2 = (None, None, None, None, None)
    assert decode_value_row(encode_value_row(row2, types), types) == row2


def test_durable_store_roundtrip(tmp_path):
    d = str(tmp_path / "ckpt")
    s1 = DurableStateStore(d)
    s1.ingest(7, 2, {b"a": b"row-a", b"b": b"row-b"}, set())
    s1.commit(2)
    s1.ingest(7, 3, {b"c": b"row-c"}, {b"a"})
    s1.ingest(9, 3, {b"x": b"row-x"}, set())
    s1.commit(3)

    s2 = DurableStateStore(d)
    assert s2.committed_epoch == 3
    assert dict(s2.iter_table(7)) == {b"b": b"row-b", b"c": b"row-c"}
    assert dict(s2.iter_table(9)) == {b"x": b"row-x"}

    # compaction folds segments without changing the view
    s2.log.compact()
    s3 = DurableStateStore(d)
    assert dict(s3.iter_table(7)) == {b"b": b"row-b", b"c": b"row-c"}
    assert s3.committed_epoch == 3


def test_mv_created_after_last_checkpoint_rebackfills(tmp_path):
    """Crash in the window between CREATE MV (logged immediately) and the
    next checkpoint (which would persist its state): recovery must re-run
    the backfill snapshot from the recovered upstream."""
    d = str(tmp_path / "db")
    child = textwrap.dedent(f"""
        import os, sys
        from risingwave_tpu.frontend import Session
        s = Session(data_dir={d!r})
        s.run_sql("CREATE TABLE t (k BIGINT, v BIGINT)")
        s.run_sql("INSERT INTO t VALUES (1,10),(2,20)")
        s.flush()                      # t's rows durably committed
        s.run_sql('''CREATE MATERIALIZED VIEW m AS
            SELECT k, v * 2 AS d FROM t''')
        # crash BEFORE any checkpoint that includes m's state
        os._exit(0)
    """)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    res = subprocess.run([sys.executable, "-c", child], env=env,
                         capture_output=True, text=True, timeout=600,
                         cwd=os.path.dirname(os.path.dirname(
                             os.path.abspath(__file__))))
    assert res.returncode == 0, res.stderr[-2000:]

    from risingwave_tpu.frontend import Session
    s = Session(data_dir=d)
    assert sorted(s.mv_rows("m")) == [(1, 20), (2, 40)]


def test_empty_flush_adds_no_segments(tmp_path):
    d = str(tmp_path / "db")
    from risingwave_tpu.frontend import Session
    s = Session(data_dir=d)
    s.run_sql("CREATE TABLE t (k BIGINT)")
    s.run_sql("INSERT INTO t VALUES (1)")
    s.flush()
    n0 = len(s.store.log._read_manifest()["segments"])
    for _ in range(5):
        s.flush()   # nothing new to persist
    m = s.store.log._read_manifest()
    assert len(m["segments"]) == n0
    assert m["committed_epoch"] == s.store.committed_epoch


def test_drop_tombstones_durable_state(tmp_path):
    d = str(tmp_path / "db")
    from risingwave_tpu.frontend import Session
    s = Session(data_dir=d)
    s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY)")
    tid = s.catalog.tables["t"].table_id
    s.run_sql("INSERT INTO t VALUES (1),(2),(3)")
    s.flush()
    s.run_sql("DROP TABLE t")
    s.flush()
    assert s.store.table_len(tid) == 0

    s2 = Session(data_dir=d)
    assert "t" not in s2.catalog.tables
    assert s2.store.table_len(tid) == 0   # not resurrected from old segments
    # compaction discards the dead rows entirely
    s2.store.log.compact()
    _, tables = s2.store.log.load_tables()
    assert tid not in tables


def test_crash_recovery_e2e(tmp_path):
    d = str(tmp_path / "db")
    child = textwrap.dedent(f"""
        import json, os, sys
        from risingwave_tpu.frontend import Session
        s = Session(data_dir={d!r})
        s.run_sql('''
            CREATE TABLE events (k BIGINT, cat VARCHAR, v BIGINT);
            CREATE MATERIALIZED VIEW agg AS
              SELECT cat, COUNT(*) AS cnt, SUM(v) AS total
              FROM events GROUP BY cat
        ''')
        s.run_sql("INSERT INTO events VALUES (1,'a',10),(2,'b',20),(3,'a',30)")
        s.flush()
        s.run_sql("INSERT INTO events VALUES (4,'b',5),(5,'c',7)")
        s.flush()
        # one more insert that is NOT checkpointed: must be lost on crash
        s.run_sql("INSERT INTO events VALUES (6,'z',999)")
        s.tick(generate=False, checkpoint=False)
        print("EXPECT " + json.dumps(sorted(s.mv_rows('agg'))))
        sys.stdout.flush()
        os._exit(0)   # crash: no graceful shutdown, no final checkpoint
    """)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("TPU_LIBRARY_PATH", None)
    res = subprocess.run([sys.executable, "-c", child], env=env,
                         capture_output=True, text=True, timeout=600,
                         cwd=os.path.dirname(os.path.dirname(
                             os.path.abspath(__file__))))
    assert res.returncode == 0, res.stderr[-2000:]
    line = [l for l in res.stdout.splitlines() if l.startswith("EXPECT ")][0]
    pre_crash = [tuple(r) for r in json.loads(line[len("EXPECT "):])]
    # the 'z' row was never checkpointed
    committed = sorted(r for r in pre_crash if r[0] != "z")
    assert ("z", 1, 999) in pre_crash

    from risingwave_tpu.frontend import Session
    s = Session(data_dir=d)
    assert sorted(s.mv_rows("agg")) == committed
    assert sorted(s.run_sql("SELECT k, cat, v FROM events")) == [
        (1, "a", 10), (2, "b", 20), (3, "a", 30), (4, "b", 5), (5, "c", 7)]

    # the recovered session keeps streaming: new DML folds into the MV
    s.run_sql("INSERT INTO events VALUES (7,'a',100)")
    s.flush()
    got = {r[0]: (r[1], r[2]) for r in s.mv_rows("agg")}
    assert got["a"] == (3, 140)
    assert got["b"] == (2, 25)
    assert got["c"] == (1, 7)

    # and survives a SECOND recovery
    s2 = Session(data_dir=d)
    assert sorted(s2.mv_rows("agg")) == sorted(s.mv_rows("agg"))
    # row ids continued above the recovered ones: all 6 rows distinct
    assert len(s2.run_sql("SELECT k, cat, v FROM events")) == 6


def test_folded_segment_name_never_collides_across_restart(tmp_path):
    """Advisor r4: _compact_seq is process-local; a fold after restart must
    not regenerate (and overwrite) an existing folded segment's name."""
    log = CheckpointLog(str(tmp_path), compact_after=1000)
    log.append_epoch(1, {7: {b"a": b"1"}})
    log.append_epoch(2, {7: {b"b": b"2"}})
    log.compact()
    first = log._read_manifest()["segments"]
    assert len(first) == 1 and ".c1-" in first[0]

    # fresh process: seq resets to 0; same committed epoch gets new segments
    log2 = CheckpointLog(str(tmp_path), compact_after=1000)
    log2.append_epoch(2, {7: {b"c": b"3"}})
    log2.compact()
    folded = log2._read_manifest()["segments"]
    assert len(folded) == 1
    # the per-process uuid token keeps the new fold's name distinct from
    # the still-live pre-restart fold
    assert folded[0] != first[0]
    _, tables = log2.load_tables()
    assert tables[7] == {b"a": b"1", b"b": b"2", b"c": b"3"}


def test_load_tables_retries_when_compactor_deletes_segment(tmp_path):
    """Advisor r4: a reader that fetched the manifest just before a
    compaction swap must converge by re-reading, not raise FileNotFound."""
    log = CheckpointLog(str(tmp_path), compact_after=1000)
    log.append_epoch(1, {7: {b"a": b"1"}})
    log.append_epoch(2, {7: {b"b": b"2"}})

    reader = CheckpointLog(str(tmp_path), compact_after=1000)
    stale = reader._read_manifest()
    log.compact()  # deletes the base segments the stale manifest references

    # simulate the race: first manifest read returns the stale snapshot
    calls = {"n": 0}
    real = reader._read_manifest

    def flaky():
        calls["n"] += 1
        return stale if calls["n"] == 1 else real()

    reader._read_manifest = flaky
    epoch, tables = reader.load_tables()
    assert epoch == 2
    assert tables[7] == {b"a": b"1", b"b": b"2"}
