"""System catalogs (pg_catalog / information_schema / rw_catalog —
VERDICT r3 missing #8): BI-tool introspection over the live catalog.
"""

from risingwave_tpu.frontend import Session


def _session():
    s = Session()
    s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, name VARCHAR)")
    s.run_sql("CREATE SOURCE src (auction BIGINT, price BIGINT) "
              "WITH (connector = 'nexmark', nexmark_table = 'bid')")
    s.run_sql("CREATE MATERIALIZED VIEW m AS SELECT k, name FROM t")
    s.flush()
    return s


def test_pg_tables_and_matviews():
    s = _session()
    tables = sorted(r[1] for r in s.run_sql("SELECT * FROM pg_tables"))
    assert tables == ["src", "t"]
    mvs = [r[1] for r in s.run_sql("SELECT * FROM pg_catalog.pg_matviews")]
    assert mvs == ["m"]
    s.close()


def test_information_schema():
    s = _session()
    kinds = dict(
        (r[0], r[1]) for r in s.run_sql(
            "SELECT table_name, table_type FROM information_schema.tables"))
    assert kinds["t"] == "BASE TABLE"
    assert kinds["m"] == "MATERIALIZED VIEW"
    cols = sorted(s.run_sql(
        "SELECT column_name, ordinal_position, data_type "
        "FROM information_schema.columns WHERE table_name = 't'"))
    assert cols == [("k", 1, "bigint"), ("name", 2, "varchar")]
    s.close()


def test_rw_relations_and_filtering():
    s = _session()
    got = dict(s.run_sql("SELECT name, kind FROM rw_catalog.rw_relations"))
    assert got == {"t": "table", "src": "source",
                   "m": "materialized view"}
    only_mv = [r[0] for r in s.run_sql(
        "SELECT name FROM rw_relations WHERE kind = 'materialized view'")]
    assert only_mv == ["m"]
    s.close()
