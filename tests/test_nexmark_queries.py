"""NEXmark queries end-to-end in SQL, checked against an independent Python
recomputation of the same deterministic generator stream (reference: the
query definitions in src/tests/simulation/src/nexmark/q*.sql and the golden
outputs of e2e_test/streaming/nexmark/)."""

import collections

import pytest

from risingwave_tpu.common import chunk_to_rows
from risingwave_tpu.connector.nexmark import (
    AUCTION_SCHEMA, BID_SCHEMA, PERSON_SCHEMA, NexmarkConfig, NexmarkGenerator,
)
from risingwave_tpu.frontend import Session

CAP = 64
TICKS = 4

DDL = """
CREATE SOURCE bid (auction BIGINT, bidder BIGINT, price BIGINT,
  channel VARCHAR, url VARCHAR, date_time TIMESTAMP, extra VARCHAR)
WITH (connector = 'nexmark', nexmark_table = 'bid');
CREATE SOURCE auction (id BIGINT, item_name VARCHAR, description VARCHAR,
  initial_bid BIGINT, reserve BIGINT, date_time TIMESTAMP,
  expires TIMESTAMP, seller BIGINT, category BIGINT, extra VARCHAR)
WITH (connector = 'nexmark', nexmark_table = 'auction');
CREATE SOURCE person (id BIGINT, name VARCHAR, email_address VARCHAR,
  credit_card VARCHAR, city VARCHAR, state VARCHAR, date_time TIMESTAMP,
  extra VARCHAR)
WITH (connector = 'nexmark', nexmark_table = 'person')
"""


def make_session() -> Session:
    s = Session(source_chunk_capacity=CAP, chunks_per_tick=1)
    s.run_sql(DDL)
    return s


def replay(table: str, n_chunks: int):
    """The exact rows a session source leaf produced (same seed/config)."""
    gen = NexmarkGenerator(NexmarkConfig(chunk_capacity=CAP), seed=42)
    fn = {"bid": gen.next_bid_chunk, "auction": gen.next_auction_chunk,
          "person": gen.next_person_chunk}[table]
    schema = {"bid": BID_SCHEMA, "auction": AUCTION_SCHEMA,
              "person": PERSON_SCHEMA}[table]
    rows = []
    for _ in range(n_chunks):
        rows.extend(chunk_to_rows(fn(), schema))
    return rows


SEC = 1_000_000


def run_mv(sql: str, name: str, ticks: int = TICKS):
    s = make_session()
    s.run_sql(sql)
    for _ in range(ticks):
        s.tick()
    return sorted(s.mv_rows(name))


def test_q1_currency_conversion():
    got = run_mv("""CREATE MATERIALIZED VIEW q1 AS
        SELECT auction, bidder, price * 89 / 100 AS price, date_time
        FROM bid""", "q1")
    bids = replay("bid", TICKS)
    exp = sorted((b[0], b[1], b[2] * 89 // 100, b[5]) for b in bids)
    assert got == exp


def test_q2_filter():
    got = run_mv("""CREATE MATERIALIZED VIEW q2 AS
        SELECT auction, price FROM bid
        WHERE auction % 123 = 0 OR auction % 125 = 0""", "q2")
    bids = replay("bid", TICKS)
    exp = sorted((b[0], b[2]) for b in bids
                 if b[0] % 123 == 0 or b[0] % 125 == 0)
    assert got == exp


def test_q3_join_filter():
    got = run_mv("""CREATE MATERIALIZED VIEW q3 AS
        SELECT P.name, P.city, P.state, A.id
        FROM auction AS A INNER JOIN person AS P on A.seller = P.id
        WHERE A.category = 10
          AND (P.state = 'OR' OR P.state = 'ID' OR P.state = 'CA')""",
        "q3", ticks=6)
    auctions = replay("auction", 6)
    persons = replay("person", 6)
    # NEXmark person ids repeat across events: a true multiset join
    exp = [
        (p[1], p[4], p[5], a[0])
        for a in auctions if a[8] == 10
        for p in persons
        if p[0] == a[7] and p[5] in ("OR", "ID", "CA")
    ]
    assert got == sorted(exp)
    assert len(got) > 0  # non-trivial


@pytest.mark.slow
def test_q4_avg_final_price():
    got = run_mv("""CREATE MATERIALIZED VIEW q4 AS
        SELECT Q.category, AVG(Q.final) as avg
        FROM (
            SELECT MAX(B.price) AS final, A.category
            FROM auction A, bid B
            WHERE A.id = B.auction
              AND B.date_time BETWEEN A.date_time AND A.expires
            GROUP BY A.id, A.category
        ) Q
        GROUP BY Q.category""", "q4", ticks=6)
    auctions = replay("auction", 6)
    bids = replay("bid", 6)
    finals: dict = {}
    for a in auctions:
        for b in bids:
            if a[0] == b[0] and a[5] <= b[5] <= a[6]:
                key = (a[0], a[8])
                finals[key] = max(finals.get(key, 0), b[2])
    per_cat = collections.defaultdict(list)
    for (aid, cat), final in finals.items():
        per_cat[cat].append(final)
    exp = sorted((cat, sum(v) / len(v)) for cat, v in per_cat.items())
    assert len(got) > 0
    assert [g[0] for g in got] == [e[0] for e in exp]
    for g, e in zip(got, exp):
        assert abs(g[1] - e[1]) < 1e-6


def test_q5_hot_items():
    got = run_mv("""CREATE MATERIALIZED VIEW q5 AS
        SELECT AuctionBids.auction, AuctionBids.num FROM (
            SELECT bid.auction, count(*) AS num, window_start AS starttime
            FROM HOP(bid, date_time, INTERVAL '2' SECOND, INTERVAL '10' SECOND)
            GROUP BY window_start, bid.auction
        ) AS AuctionBids
        JOIN (
            SELECT max(CountBids.num) AS maxn, CountBids.starttime_c
            FROM (
                SELECT count(*) AS num, window_start AS starttime_c
                FROM HOP(bid, date_time, INTERVAL '2' SECOND,
                         INTERVAL '10' SECOND)
                GROUP BY bid.auction, window_start
            ) AS CountBids
            GROUP BY CountBids.starttime_c
        ) AS MaxBids
        ON AuctionBids.starttime = MaxBids.starttime_c
           AND AuctionBids.num = MaxBids.maxn""", "q5")
    bids = replay("bid", TICKS)
    counts: dict = collections.defaultdict(int)
    slide, size = 2 * SEC, 10 * SEC
    n = size // slide
    for b in bids:
        ts = b[5]
        base = (ts // slide) * slide
        for i in range(n):
            ws = base - i * slide
            if ws <= ts < ws + size:
                counts[(ws, b[0])] += 1
    maxn: dict = collections.defaultdict(int)
    for (ws, auction), c in counts.items():
        maxn[ws] = max(maxn[ws], c)
    exp = sorted(
        (auction, c) for (ws, auction), c in counts.items()
        if c == maxn[ws])
    assert got == exp and len(got) > 0


def test_q7_highest_bid():
    got = run_mv("""CREATE MATERIALIZED VIEW q7 AS
        SELECT B.auction, B.price, B.bidder, B.date_time
        FROM bid B
        JOIN (
            SELECT MAX(price) AS maxprice, window_end as date_time
            FROM TUMBLE(bid, date_time, INTERVAL '10' SECOND)
            GROUP BY window_end
        ) B1 ON B.price = B1.maxprice
        WHERE B.date_time BETWEEN B1.date_time - INTERVAL '10' SECOND
              AND B1.date_time""", "q7")
    bids = replay("bid", TICKS)
    size = 10 * SEC
    win_max: dict = collections.defaultdict(int)
    for b in bids:
        we = (b[5] // size) * size + size
        win_max[we] = max(win_max[we], b[2])
    exp = []
    for b in bids:
        for we, mx in win_max.items():
            if b[2] == mx and we - size <= b[5] <= we:
                exp.append((b[0], b[2], b[1], b[5]))
    assert got == sorted(exp) and len(got) > 0


def test_q8_new_users():
    got = run_mv("""CREATE MATERIALIZED VIEW q8 AS
        SELECT P.id, P.name, P.starttime
        FROM (
            SELECT id, name, window_start AS starttime,
                   window_end AS endtime
            FROM TUMBLE(person, date_time, INTERVAL '10' SECOND)
            GROUP BY id, name, window_start, window_end
        ) P
        JOIN (
            SELECT seller, window_start AS starttime,
                   window_end AS endtime
            FROM TUMBLE(auction, date_time, INTERVAL '10' SECOND)
            GROUP BY seller, window_start, window_end
        ) A ON P.id = A.seller AND P.starttime = A.starttime
               AND P.endtime = A.endtime""", "q8", ticks=6)
    persons = replay("person", 6)
    auctions = replay("auction", 6)
    size = 10 * SEC
    p_windows = {(p[0], p[1], (p[6] // size) * size) for p in persons}
    a_windows = {(a[7], (a[5] // size) * size) for a in auctions}
    exp = sorted(
        {(pid, name, ws) for (pid, name, ws) in p_windows
         if (pid, ws) in a_windows})
    assert got == exp and len(got) > 0
