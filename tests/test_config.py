"""Config system + system params + CLI (coverage #6/#7/#83)."""

import subprocess
import sys
import os

import pytest

from risingwave_tpu.common.config import RwConfig, load_config
from risingwave_tpu.frontend import Session


class TestConfig:
    def test_defaults(self):
        cfg = load_config()
        assert cfg.streaming.barrier_interval_ms == 1000
        assert cfg.streaming.checkpoint_frequency == 10
        assert cfg.server.port == 4566

    def test_toml_layering_and_overrides(self, tmp_path):
        p = tmp_path / "rw.toml"
        p.write_text("""
[streaming]
checkpoint_frequency = 4

[server]
port = 5433
""")
        cfg = load_config(str(p), **{"streaming.chunk_capacity": 256})
        assert cfg.streaming.checkpoint_frequency == 4
        assert cfg.server.port == 5433
        assert cfg.streaming.chunk_capacity == 256
        assert cfg.streaming.barrier_interval_ms == 1000   # untouched default

    def test_fallback_parser_subset(self, tmp_path):
        """The tomllib-less fallback parser (py3.10) handles the config
        subset: sections, ints/floats/bools, quoted strings — including
        '#' INSIDE a quoted value — and trailing comments."""
        from risingwave_tpu.common.config import _parse_toml_subset
        data = _parse_toml_subset("""
# header comment
[storage]
data_dir = "/tmp/run#3"          # trailing comment
compactors = 2

[streaming]
coschedule = true
slow_epoch_threshold_ms = 1.5
""")
        assert data["storage"]["data_dir"] == "/tmp/run#3"
        assert data["storage"]["compactors"] == 2
        assert data["streaming"]["coschedule"] is True
        assert data["streaming"]["slow_epoch_threshold_ms"] == 1.5

    def test_unknown_keys_rejected(self, tmp_path):
        p = tmp_path / "rw.toml"
        p.write_text("[streaming]\nbogus_key = 1\n")
        with pytest.raises(ValueError, match="bogus_key"):
            load_config(str(p))
        with pytest.raises(ValueError, match="section"):
            load_config(**{"nosection.x": 1})

    def test_session_from_rw_config(self):
        cfg = load_config(**{"streaming.checkpoint_frequency": 3,
                             "streaming.chunk_capacity": 128})
        s = Session(rw_config=cfg)
        assert s.checkpoint_frequency == 3
        assert s.config.chunk_capacity == 128


class TestSystemParams:
    def test_set_and_show(self):
        s = Session()
        s.run_sql("SET checkpoint_frequency = 2")
        assert s.checkpoint_frequency == 2
        s.run_sql("SET in_flight_barrier_nums TO 4")
        assert s.in_flight_barriers == 4
        params = dict(s.run_sql("SHOW PARAMETERS"))
        assert params["checkpoint_frequency"] == "2"
        with pytest.raises(Exception, match="parameter"):
            s.run_sql("SET nonsense = 1")

    def test_set_applies_to_checkpoints(self, tmp_path):
        s = Session(data_dir=str(tmp_path / "db"))
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY)")
        s.run_sql("SET checkpoint_frequency = 1")
        s.run_sql("INSERT INTO t VALUES (1)")
        s.tick()          # every tick checkpoints now
        s._drain_inflight()
        assert s.store.committed_epoch > 0


class TestCli:
    def test_sql_subcommand(self):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PALLAS_AXON_POOL_IPS", None)
        res = subprocess.run(
            [sys.executable, "-m", "risingwave_tpu", "sql",
             "CREATE TABLE t (k BIGINT PRIMARY KEY); "
             "INSERT INTO t VALUES (41); FLUSH; SELECT k + 1 FROM t"],
            capture_output=True, text=True, timeout=600, env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        assert res.returncode == 0, res.stderr[-2000:]
        assert res.stdout.strip().splitlines()[-1] == "42"


class TestCtl:
    def test_ctl_inspection(self, tmp_path):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PALLAS_AXON_POOL_IPS", None)
        cwd = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        d = str(tmp_path / "db")
        res = subprocess.run(
            [sys.executable, "-m", "risingwave_tpu", "sql",
             "CREATE TABLE t (k BIGINT PRIMARY KEY); "
             "CREATE MATERIALIZED VIEW m AS SELECT count(*) AS c FROM t; "
             "FLUSH", "--data-dir", d],
            capture_output=True, text=True, timeout=600, env=env, cwd=cwd)
        assert res.returncode == 0, res.stderr[-1500:]
        res = subprocess.run(
            [sys.executable, "-m", "risingwave_tpu", "ctl", "jobs",
             "--data-dir", d],
            capture_output=True, text=True, timeout=600, env=env, cwd=cwd)
        assert res.returncode == 0, res.stderr[-1500:]
        assert "TABLE\tt" in res.stdout and "MV\tm" in res.stdout
        res = subprocess.run(
            [sys.executable, "-m", "risingwave_tpu", "ctl", "trace",
             "--data-dir", d],
            capture_output=True, text=True, timeout=600, env=env, cwd=cwd)
        assert res.returncode == 0 and "job 'm':" in res.stdout
