"""Prometheus metrics endpoint (observability export — VERDICT r3
missing #9): Session.metrics() rendered in exposition format and served
over HTTP for a stock scrape config.
"""

import urllib.request

from risingwave_tpu.frontend import Session
from risingwave_tpu.frontend.prometheus import render_metrics, serve_metrics

DDL = """CREATE SOURCE bid (auction BIGINT, price BIGINT)
    WITH (connector = 'nexmark', nexmark_table = 'bid')"""


def _session():
    s = Session(source_chunk_capacity=64, checkpoint_frequency=2)
    s.run_sql(DDL)
    s.run_sql("CREATE MATERIALIZED VIEW m AS "
              "SELECT auction, count(*) AS n FROM bid GROUP BY auction")
    for _ in range(3):
        s.tick()
    s._drain_inflight()
    return s


def test_render_exposition_format():
    s = _session()
    text = render_metrics(s)
    assert "rw_epoch " in text
    assert 'rw_barrier_latency_ms{quantile="0.99"}' in text
    assert 'rw_executor_counter{job="m"' in text
    assert 'rw_state_bytes{job="m"}' in text
    # every sample line is "name{labels} value" or "name value"
    for line in text.splitlines():
        if line.startswith("#") or not line:
            continue
        parts = line.rsplit(" ", 1)
        assert len(parts) == 2 and float(parts[1]) >= 0
    s.close()


def test_http_scrape():
    s = _session()
    srv = serve_metrics(s)
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics", timeout=10) as r:
            assert r.status == 200
            assert "text/plain" in r.headers["Content-Type"]
            body = r.read().decode()
        assert "rw_epoch" in body and "rw_executor_counter" in body
    finally:
        srv.close()
        s.close()


def test_render_slow_epoch_counter():
    s = _session()
    s.run_sql("SET slow_epoch_threshold_ms = 0.0001")
    s.tick()
    s._drain_inflight()
    text = render_metrics(s)
    line = next(ln for ln in text.splitlines()
                if ln.startswith("rw_slow_epoch_total"))
    assert float(line.split(" ")[-1]) >= 1
    s.close()
