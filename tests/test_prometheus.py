"""Prometheus metrics endpoint (observability export — VERDICT r3
missing #9): Session.metrics() rendered in exposition format and served
over HTTP for a stock scrape config.
"""

import urllib.request

from risingwave_tpu.frontend import Session
from risingwave_tpu.frontend.prometheus import render_metrics, serve_metrics

DDL = """CREATE SOURCE bid (auction BIGINT, price BIGINT)
    WITH (connector = 'nexmark', nexmark_table = 'bid')"""


def _session():
    s = Session(source_chunk_capacity=64, checkpoint_frequency=2)
    s.run_sql(DDL)
    s.run_sql("CREATE MATERIALIZED VIEW m AS "
              "SELECT auction, count(*) AS n FROM bid GROUP BY auction")
    for _ in range(3):
        s.tick()
    s._drain_inflight()
    return s


def test_render_exposition_format():
    s = _session()
    text = render_metrics(s)
    assert "rw_epoch " in text
    assert 'rw_barrier_latency_ms{quantile="0.99"}' in text
    assert 'rw_executor_counter{job="m"' in text
    assert 'rw_state_bytes{job="m"}' in text
    # every sample line is "name{labels} value" or "name value"
    for line in text.splitlines():
        if line.startswith("#") or not line:
            continue
        parts = line.rsplit(" ", 1)
        assert len(parts) == 2 and float(parts[1]) >= 0
    s.close()


def test_http_scrape():
    s = _session()
    srv = serve_metrics(s)
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics", timeout=10) as r:
            assert r.status == 200
            assert "text/plain" in r.headers["Content-Type"]
            body = r.read().decode()
        assert "rw_epoch" in body and "rw_executor_counter" in body
    finally:
        srv.close()
        s.close()


_NAME_RE = r"[a-zA-Z_:][a-zA-Z0-9_:]*"


def _parse_exposition(text: str) -> dict:
    """Strict-ish exposition parser: returns {family: {"help": str,
    "type": str, "samples": [(name, labels, value)]}} and asserts the
    line grammar as it goes — the lint every past and future metric
    section must pass."""
    import re

    families: dict = {}
    sample_re = re.compile(
        rf"^({_NAME_RE})(?:\{{(.*)\}})? (\S+)$")
    label_re = re.compile(rf'^({_NAME_RE})="((?:[^"\\]|\\.)*)"$')
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP "):
            _, _, rest = line.partition("# HELP ")
            name, _, help_text = rest.partition(" ")
            assert re.fullmatch(_NAME_RE, name), line
            assert help_text.strip(), f"empty HELP: {line!r}"
            fam = families.setdefault(name, {"samples": []})
            assert "help" not in fam, f"duplicate HELP for {name}"
            fam["help"] = help_text
        elif line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, kind = rest.partition(" ")
            assert kind in ("counter", "gauge", "histogram",
                            "summary", "untyped"), line
            fam = families.setdefault(name, {"samples": []})
            assert "type" not in fam, f"duplicate TYPE for {name}"
            fam["type"] = kind
        elif line.startswith("#"):
            raise AssertionError(f"unparseable comment line: {line!r}")
        else:
            m = sample_re.match(line)
            assert m, f"unparseable sample line: {line!r}"
            name, labels_raw, value = m.groups()
            labels = {}
            if labels_raw:
                for pair in re.split(r",(?=[a-zA-Z_])", labels_raw):
                    lm = label_re.match(pair)
                    assert lm, f"bad label pair {pair!r} in {line!r}"
                    labels[lm.group(1)] = lm.group(2)
            float(value)                    # must parse
            # a sample belongs to the family of its metric name (no
            # _bucket/_sum suffixes are emitted by this codebase)
            assert name in families, \
                f"sample {name!r} has no preceding HELP/TYPE"
            families[name]["samples"].append((name, labels, value))
    return families


def test_exposition_lint_every_family_has_help_and_type(tmp_path):
    """Satellite (ISSUE 12): parse the FULL exposition from a session
    exercising many metric sections and assert every rw_* family is
    well-formed — valid names, quoted labels, one HELP + one TYPE per
    family, every sample preceded by its family header. A lint for all
    past and future sections, not just the profiling plane's."""
    s = Session(workers=1, seed=11, data_dir=str(tmp_path / "lint"))
    try:
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, v BIGINT)")
        s.run_sql("CREATE MATERIALIZED VIEW lm AS SELECT v, count(*) "
                  "AS c FROM t GROUP BY v")
        s.run_sql("INSERT INTO t VALUES (1, 10), (2, 20)")
        s.flush()
        s.run_sql("SELECT v, c FROM lm")       # serving-plane counters
        families = _parse_exposition(render_metrics(s))
        assert families, "empty exposition"
        for name, fam in families.items():
            assert name.startswith("rw_"), f"non-rw_ family {name}"
            assert "help" in fam, f"{name} missing HELP"
            assert "type" in fam, f"{name} missing TYPE"
            # a declared family MAY legitimately be empty this scrape
            # (e.g. rw_chaos_injection_total with no chaos installed);
            # samples without a declaration are caught in the parser
        # the sections this cluster shape must light up (PR 1 core, PR 2
        # storage, PR 8 serving, PR 9 chaos, PR 10 autoscaler, PR 12
        # profiling, PR 16 barrier observatory, PR 18 leadership) — a
        # renamed family fails here loudly
        for expected in ("rw_epoch", "rw_executor_counter",
                         "rw_state_bytes", "rw_worker_up",
                         "rw_storage_stat", "rw_serving_stat",
                         "rw_chaos_injection_total", "rw_chaos_stat",
                         "rw_autoscaler_stat", "rw_autoscaler_enabled",
                         "rw_dispatch_total", "rw_dispatch_seconds",
                         "rw_compile_total", "rw_hbm_bytes",
                         "rw_hbm_headroom_bytes",
                         "rw_barrier_stage_seconds",
                         "rw_barrier_inflight", "rw_barrier_total",
                         "rw_leader_term", "rw_leader_is_writer",
                         "rw_failover_total",
                         "rw_failover_duration_seconds"):
            assert expected in families, \
                f"{expected} missing from exposition: {sorted(families)}"
    finally:
        s.close()


def test_hetero_families_present_and_linted():
    """Satellite (ISSUE 19): a tick-compiled session exports the
    rw_hetero_* family — schedule shape, recompile counter, per-group
    membership, per-job attribution weights — and every family passes
    the exposition lint above."""
    from risingwave_tpu.frontend.build import BuildConfig

    s = Session(config=BuildConfig(tick_compiler=True,
                                   agg_table_capacity=1 << 12),
                source_chunk_capacity=64)
    try:
        s.run_sql(DDL)
        s.run_sql("CREATE MATERIALIZED VIEW h0 AS SELECT auction, "
                  "sum(price + 10) AS v FROM bid GROUP BY auction")
        s.run_sql("CREATE MATERIALIZED VIEW h1 AS SELECT auction, "
                  "sum(price + 20) AS v FROM bid GROUP BY auction")
        for _ in range(2):
            s.tick()
        families = _parse_exposition(render_metrics(s))
        for expected in ("rw_hetero_jobs",
                         "rw_hetero_dispatches_per_tick",
                         "rw_hetero_schedule_compiles",
                         "rw_hetero_group_jobs",
                         "rw_hetero_flush_weight"):
            assert expected in families, \
                f"{expected} missing: {sorted(families)}"
        jobs = families["rw_hetero_jobs"]["samples"]
        assert float(jobs[0][2]) == 2
        groups = families["rw_hetero_group_jobs"]["samples"]
        assert any(l.get("kind") == "padded" for _, l, _ in groups)
        weights = families["rw_hetero_flush_weight"]["samples"]
        assert {l["job"] for _, l, _ in weights} == {"h0", "h1"}
    finally:
        s.close()


def test_render_slow_epoch_counter():
    s = _session()
    s.run_sql("SET slow_epoch_threshold_ms = 0.0001")
    s.tick()
    s._drain_inflight()
    text = render_metrics(s)
    line = next(ln for ln in text.splitlines()
                if ln.startswith("rw_slow_epoch_total"))
    assert float(line.split(" ")[-1]) >= 1
    s.close()
