"""OverWindow (general + EOWC) and ProjectSet/table functions
(VERDICT r2 item 7). Expected values are recomputed by straightforward
host models inside the tests."""

import asyncio

import pytest

from risingwave_tpu.common.chunk import (
    OP_DELETE, OP_INSERT, make_chunk,
)
from risingwave_tpu.common.types import INT64, Field, Schema
from risingwave_tpu.frontend import Session
from risingwave_tpu.ops.topn import OrderSpec
from risingwave_tpu.stream.executor import collect_until_barrier
from risingwave_tpu.stream.message import Barrier, Watermark
from risingwave_tpu.stream.over_window import (
    EowcOverWindowExecutor, OverWindowExecutor, WindowCall,
    compute_window_values,
)
from risingwave_tpu.stream.source import MockSource

S3 = Schema((Field("k", INT64), Field("g", INT64), Field("v", INT64)))


class TestHostModel:
    def test_compute_window_values_ranks_and_aggs(self):
        calls = (
            WindowCall("row_number", INT64, partition_by=(1,),
                       order_by=(OrderSpec(2),)),
            WindowCall("rank", INT64, partition_by=(1,),
                       order_by=(OrderSpec(2),)),
            WindowCall("dense_rank", INT64, partition_by=(1,),
                       order_by=(OrderSpec(2),)),
            WindowCall("sum", INT64, arg=2, partition_by=(1,),
                       order_by=(OrderSpec(2),)),
        )
        rows = [(1, 7, 10), (2, 7, 10), (3, 7, 30), (4, 7, 20)]
        got = compute_window_values(rows, calls, (0,))
        # peers (10,10): rank 1,1 then 20 → rank 3, 30 → rank 4
        assert got[(1,)][1] == 1 and got[(2,)][1] == 1
        assert got[(4,)][1] == 3 and got[(3,)][1] == 4
        assert got[(3,)][2] == 3          # dense_rank
        # RANGE running sum includes peers: rows 1,2 both see 20
        assert got[(1,)][3] == 20 and got[(2,)][3] == 20
        assert got[(4,)][3] == 40 and got[(3,)][3] == 70

    def test_lag_lead(self):
        calls = (
            WindowCall("lag", INT64, arg=2, partition_by=(1,),
                       order_by=(OrderSpec(2),)),
            WindowCall("lead", INT64, arg=2, partition_by=(1,),
                       order_by=(OrderSpec(2),)),
        )
        rows = [(1, 7, 10), (2, 7, 20), (3, 7, 30)]
        got = compute_window_values(rows, calls, (0,))
        assert got[(1,)] == (None, 20)
        assert got[(2,)] == (10, 30)
        assert got[(3,)] == (20, None)


def _fold(chunks, schema):
    """Rows with positive net count (a retraction may precede its insert
    when folding a recovered executor's delta stream from empty)."""
    from risingwave_tpu.common.chunk import chunk_to_rows
    acc = {}
    for c in chunks:
        for op, row in chunk_to_rows(c, schema, with_ops=True):
            acc[row] = acc.get(row, 0) + (1 if op in (0, 3) else -1)
    return {row for row, n in acc.items() if n > 0}


class TestGeneralExecutor:
    def test_retraction_on_rank_change(self):
        calls = (WindowCall("row_number", INT64, partition_by=(1,),
                            order_by=(OrderSpec(2),)),)
        msgs = [
            Barrier.new(1),
            make_chunk(S3, [(1, 7, 20), (2, 7, 30)], capacity=4),
            Barrier.new(2),
            # new smallest row displaces both ranks
            make_chunk(S3, [(3, 7, 10)], capacity=4),
            Barrier.new(3),
            make_chunk(S3, [(1, 7, 20)], ops=[OP_DELETE], capacity=4),
            Barrier.new(4),
        ]
        ex = OverWindowExecutor(MockSource(S3, msgs), calls, pk_indices=(0,))
        chunks = asyncio.run(self._collect(ex, 4))
        final = _fold(chunks, ex.schema)
        assert final == {(3, 7, 10, 1), (2, 7, 30, 2)}

    async def _collect(self, ex, n):
        chunks, _, _ = await collect_until_barrier(ex.execute(), n)
        return chunks


class TestEowcExecutor:
    def test_running_emission(self):
        calls = (
            WindowCall("row_number", INT64, partition_by=(1,),
                       order_by=(OrderSpec(2),)),
            WindowCall("sum", INT64, arg=2, partition_by=(1,),
                       order_by=(OrderSpec(2),)),
        )
        msgs = [
            Barrier.new(1),
            make_chunk(S3, [(1, 7, 10), (2, 7, 10)], capacity=4),
            make_chunk(S3, [(3, 7, 20)], capacity=4),   # closes peers @10
            Watermark(2, 25),
            Barrier.new(2),                             # closes group @20
            Barrier.new(3),
        ]
        ex = EowcOverWindowExecutor(MockSource(S3, msgs), calls,
                                    pk_indices=(0,))
        chunks = asyncio.run(self._collect(ex, 3))
        rows = _fold(chunks, ex.schema)
        # peers at v=10 share the RANGE sum (20); row 3 sums to 40
        assert rows == {(1, 7, 10, 1, 20), (2, 7, 10, 2, 20),
                        (3, 7, 20, 3, 40)}

    async def _collect(self, ex, n):
        chunks, _, _ = await collect_until_barrier(ex.execute(), n)
        return chunks


class TestSql:
    def _setup(self):
        s = Session()
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, g BIGINT, v BIGINT)")
        s.run_sql("INSERT INTO t VALUES (1,7,10),(2,7,10),(3,7,30),"
                  "(4,8,5),(5,8,15)")
        s.flush()
        return s

    def test_batch_select_window(self):
        s = self._setup()
        rows = s.run_sql(
            "SELECT k, rank() OVER (PARTITION BY g ORDER BY v) FROM t")
        assert sorted(rows) == [(1, 1), (2, 1), (3, 3), (4, 1), (5, 2)]

    def test_mv_window_updates_incrementally(self):
        s = self._setup()
        s.run_sql("CREATE MATERIALIZED VIEW w AS SELECT k, "
                  "row_number() OVER (PARTITION BY g ORDER BY v) AS rn, "
                  "sum(v) OVER (PARTITION BY g ORDER BY v) AS rs FROM t")
        s.flush()
        got = {r[0]: r[1:] for r in s.mv_rows("w")}
        assert got[(4)] == (1, 5) and got[5] == (2, 20)
        assert got[1][1] == 20 and got[2][1] == 20    # peers share sum
        # insert a new minimum into g=8: ranks shift
        s.run_sql("INSERT INTO t VALUES (6, 8, 1)")
        s.flush()
        got = {r[0]: r[1:] for r in s.mv_rows("w")}
        assert got[6] == (1, 1) and got[4] == (2, 6) and got[5] == (3, 21)

    def test_window_desc_and_lag(self):
        s = self._setup()
        rows = s.run_sql(
            "SELECT k, lag(v) OVER (PARTITION BY g ORDER BY v DESC) FROM t")
        by_k = dict(rows)
        assert by_k[3] is None          # largest in g=7
        assert by_k[5] == 30 or by_k[5] is None  # g=8 largest is 15
        assert by_k[4] == 15


class TestReviewRegressions:
    def test_count_star_window(self):
        calls = (WindowCall("count", INT64, arg=-1, partition_by=(1,),
                            order_by=(OrderSpec(2),)),)
        rows = [(1, 7, 10), (2, 7, 20), (3, 7, 30)]
        got = compute_window_values(rows, calls, (0,))
        assert got[(1,)] == (1,) and got[(2,)] == (2,) and got[(3,)] == (3,)

    def test_recovery_out_shape(self):
        """Recovered executor must retract correctly on the next change."""
        from risingwave_tpu.storage.state_store import MemoryStateStore
        from risingwave_tpu.storage.state_table import StateTable
        store = MemoryStateStore()
        st = StateTable(store, 1, S3, [0])
        calls = (WindowCall("row_number", INT64, partition_by=(1,),
                            order_by=(OrderSpec(2),)),)
        msgs1 = [Barrier.new(1),
                 make_chunk(S3, [(1, 7, 20), (2, 7, 30)], capacity=4),
                 Barrier.new(2, checkpoint=True)]
        ex1 = OverWindowExecutor(MockSource(S3, msgs1), calls,
                                 pk_indices=(0,), state_table=st)
        asyncio.run(self._collect(ex1, 2))
        store.commit(2)

        st2 = StateTable(store, 1, S3, [0])
        msgs2 = [Barrier.new(3),
                 make_chunk(S3, [(3, 7, 10)], capacity=4),
                 Barrier.new(4)]
        ex2 = OverWindowExecutor(MockSource(S3, msgs2), calls,
                                 pk_indices=(0,), state_table=st2)
        chunks = asyncio.run(self._collect(ex2, 2))
        # only the delta is emitted: ranks of rows 1,2 shift via U-/U+
        final = _fold(chunks, ex2.schema)
        assert (3, 7, 10, 1) in final
        assert (1, 7, 20, 2) in final and (2, 7, 30, 3) in final

    def test_negative_lag_offset_rejected(self):
        s = Session()
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, v BIGINT)")
        with pytest.raises(Exception, match="non-negative"):
            s.run_sql("SELECT lag(v, -1) OVER (ORDER BY k) FROM t")

    async def _collect(self, ex, n):
        chunks, _, _ = await collect_until_barrier(ex.execute(), n)
        return chunks


class TestEowcSql:
    def test_eowc_window_mv(self):
        """EMIT ON WINDOW CLOSE over-window: Sort upstream + running
        accumulators, rows finalized as the watermark passes them."""
        from risingwave_tpu.common.chunk import make_chunk as mk
        from risingwave_tpu.common.types import TIMESTAMP

        s = Session()
        s.run_sql("""CREATE SOURCE e (ts TIMESTAMP, g BIGINT, v BIGINT,
                     WATERMARK FOR ts AS ts - INTERVAL '1' SECOND)""")
        s.run_sql("""CREATE MATERIALIZED VIEW w AS
            SELECT g, v, sum(v) OVER (PARTITION BY g ORDER BY ts) AS rs
            FROM e EMIT ON WINDOW CLOSE""")
        src_schema = s.catalog.sources["e"].schema
        us = 1_000_000
        rows1 = [(1 * us, 7, 10), (2 * us, 7, 20)]
        s.feeds[0].queue.push(mk(src_schema, rows1, capacity=4,
                                 physical=True))
        s.tick(generate=False)
        # watermark = 2s - 1s = 1s; a peer group AT the watermark may still
        # grow (ts >= wm rows are not late), so nothing finalizes yet
        assert s.mv_rows("w") == []
        s.feeds[0].queue.push(mk(src_schema, [(4 * us, 7, 5)], capacity=4,
                                 physical=True))
        s.tick(generate=False)
        s.tick(generate=False)
        # watermark = 3s → ts=1s and ts=2s rows finalized
        assert sorted(s.mv_rows("w")) == [(7, 10, 10), (7, 20, 30)]


class TestProjectSet:
    def test_from_generate_series(self):
        s = Session()
        rows = s.run_sql("SELECT * FROM generate_series(2, 5)")
        assert sorted(r[0] for r in rows) == [2, 3, 4, 5]

    def test_project_set_over_table(self):
        s = Session()
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, v BIGINT)")
        s.run_sql("INSERT INTO t VALUES (1, 2), (2, 3)")
        s.flush()
        rows = s.run_sql("SELECT k, generate_series(1, v) FROM t")
        assert sorted(rows) == [(1, 1), (1, 2), (2, 1), (2, 2), (2, 3)]

    def test_project_set_mv_streams(self):
        s = Session()
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, v BIGINT)")
        s.run_sql("CREATE MATERIALIZED VIEW m AS "
                  "SELECT k, generate_series(1, v) AS e FROM t")
        s.run_sql("INSERT INTO t VALUES (1, 2)")
        s.flush()
        assert sorted(s.mv_rows("m")) == [(1, 1), (1, 2)]
        s.run_sql("INSERT INTO t VALUES (2, 1)")
        s.flush()
        assert sorted(s.mv_rows("m")) == [(1, 1), (1, 2), (2, 1)]
